"""ONE QoS admission authority (ISSUE 12 tentpole).

Admission decisions used to live in FOUR independent planes --
``DeviceWindow`` pacing (pipeline/overlap.py), ``StageScheduler``
credits and ``ReplicaGroup`` per-slot windows (pipeline/stages.py), and
the batchers (models/batching.py) -- so a frame's "priority" meant
nothing end to end: an interactive frame could jump the stage queue
only to sit behind a batch burst at the batcher.  This module is the
single authority those planes now consult: **tenant -> class ->
budget**, resolved once per frame at ingest and honored identically at
every seam (Vortex, PAPERS.md: hosting inference under tight latency
AND throughput requirements needs one scheduler, not four).

The vocabulary:

- **Priority classes** (``interactive`` / ``standard`` / ``batch`` by
  default; weights configurable) order admission everywhere a frame
  can wait.  Lower rank = more urgent.  Within one class (and one
  stream -- a stream's frames share its class) the ingest sequence
  breaks ties, so per-stream frame order and PR 3's
  anti-queue-jumping reservation discipline are preserved by
  construction: priority reorders *across* streams, never within one.
- **Promotion**: a frame within ``promote_ms`` of its
  ``frame_deadline_ms`` deadline ranks as the top class regardless of
  its own (PR 5's deadline machinery is the substrate; the promotion
  is recorded once per frame -- ``qos_promotions`` counter +
  ``gw_promote`` ring event).  Within a stream promotion is monotone
  (an earlier frame's deadline is earlier), so it cannot invert
  per-stream order either.
- **Aging**: every ``age_ms`` of queue wait improves a frame's rank by
  one class step, so the lowest class is starvation-free (bounded
  wait) even under saturating high-priority load.
- **Token buckets** rate-limit each tenant at the gateway front door
  (``rate`` requests/s, ``burst`` capacity): an over-rate frame is
  rejected before it ever touches the engine.
- **Budgets** (``budget`` = per-tenant in-flight frames) decide who
  sheds first: under overload (``max_inflight`` pipeline-wide
  in-flight frames) the scheduler picks victims over-budget-tenant
  first, then lowest class, then oldest -- so a tenant inside its
  budget keeps its SLO while the over-budget one absorbs the shed.

jax-free and import-light by design: the engine seams
(pipeline/stages.py, models/batching.py) import this module, and the
lint plane (analysis/params.py) imports :func:`qos_spec_error` as the
create-time twin of runtime validation, so pre-flight and runtime can
never disagree about what a well-formed ``qos`` block is.
"""

from __future__ import annotations

import json
import threading
import time

__all__ = ["QosScheduler", "SloTracker", "TokenBucket", "QOS_CLASSES",
           "DEFAULT_CLASS", "qos_spec_error", "slo_spec_error"]

#: default priority classes, most to least urgent; ``classes`` in the
#: ``qos`` block re-weights or extends them.
QOS_CLASSES = ("interactive", "standard", "batch")
DEFAULT_CLASS = "standard"
DEFAULT_TENANT = "default"

#: default class weights (higher = more urgent); rank order is the
#: descending-weight order.
_DEFAULT_WEIGHTS = {"interactive": 8.0, "standard": 4.0, "batch": 1.0}

PROMOTE_MS_DEFAULT = 50.0
AGE_MS_DEFAULT = 2000.0

#: Cap on LAZILY-created tenant entries (explicitly configured tenants
#: are never evicted and don't count against it).  Tenant names arrive
#: from unauthenticated clients: without a bound, cycling random names
#: grows scheduler memory and per-tenant metric cardinality forever.
#: Past the cap, unknown names share the default tenant's entry
#: (bucket + budget) -- bounded degradation, never unbounded state.
LAZY_TENANT_CAP = 1024

_TENANT_KEYS = {"rate", "burst", "budget", "class"}
_CLASS_KEYS = {"weight", "device_inflight"}
_SPEC_KEYS = {"classes", "tenants", "default_tenant", "promote_ms",
              "age_ms", "max_inflight", "session_window", "slo"}
_SLO_KEYS = {"p99_ms", "availability", "window_s"}
SLO_WINDOW_S_DEFAULT = 60.0
#: debounce between fast-burn firings for one (tenant, class) -- the
#: remediation consumer (ring event + black-box dump) must not be
#: re-triggered every result while the burn persists.
SLO_FIRE_COOLDOWN_S = 5.0


class TokenBucket:
    """Per-tenant rate limit: ``rate`` tokens/second refill into a
    ``burst``-deep bucket; each admitted frame takes one.  ``rate`` 0 =
    unlimited (the bucket never engages).  Thread-safe: the gateway's
    connection threads admit concurrently."""

    def __init__(self, rate: float = 0.0, burst: float = 1.0):
        self.rate = max(0.0, float(rate))
        self.burst = max(1.0, float(burst))
        self._level = self.burst
        self._stamp = time.monotonic()
        self._lock = threading.Lock()

    def take(self, now: float | None = None) -> bool:
        if self.rate <= 0:
            return True
        now = time.monotonic() if now is None else now
        with self._lock:
            self._level = min(
                self.burst, self._level + (now - self._stamp) * self.rate)
            self._stamp = now
            if self._level >= 1.0:
                self._level -= 1.0
                return True
            return False

    def level(self, now: float | None = None) -> float:
        if self.rate <= 0:
            return self.burst
        now = time.monotonic() if now is None else now
        with self._lock:
            return min(self.burst,
                       self._level + (now - self._stamp) * self.rate)


class _Tenant:
    """Resolved per-tenant state: bucket + budget + counters."""

    def __init__(self, name: str, spec: dict):
        self.name = name
        self.bucket = TokenBucket(spec.get("rate", 0.0),
                                  spec.get("burst", 8.0))
        self.budget = int(spec.get("budget", 0))     # 0 = unbounded
        self.default_class = str(spec.get("class", DEFAULT_CLASS))
        self.inflight = 0
        self.admitted = 0
        self.rejected = 0
        self.shed = 0

    @property
    def over_budget(self) -> bool:
        return self.budget > 0 and self.inflight > self.budget


def slo_spec_error(value) -> str | None:
    """Why an ``slo`` block is malformed, or None -- the jax-free
    create-time twin of :class:`SloTracker` construction (same
    discipline as :func:`qos_spec_error`): a typo'd objective is a
    DefinitionError at create, even under ``preflight: off``.  Shape:
    ``{class: {p99_ms: N, availability: 0..1, window_s: N}}``."""
    if isinstance(value, str):
        try:
            value = json.loads(value)
        except json.JSONDecodeError as error:
            return f"unparseable JSON ({error})"
    if not isinstance(value, dict):
        return f"expected a dict, got {type(value).__name__}"
    for name, spec in value.items():
        if not isinstance(spec, dict):
            return f"{name} must be a dict of objectives"
        bad = set(spec) - _SLO_KEYS
        if bad:
            return f"{name}: unknown keys {sorted(bad)} (one of " \
                   f"{sorted(_SLO_KEYS)})"
        if not (set(spec) & {"p99_ms", "availability"}):
            return f"{name}: declare p99_ms and/or availability"
        if "p99_ms" in spec:
            try:
                if float(spec["p99_ms"]) <= 0:
                    return f"{name}.p99_ms must be > 0"
            except (TypeError, ValueError):
                return f"{name}.p99_ms={spec['p99_ms']!r} is not a number"
        if "availability" in spec:
            try:
                availability = float(spec["availability"])
            except (TypeError, ValueError):
                return f"{name}.availability=" \
                       f"{spec['availability']!r} is not a number"
            if not 0.0 < availability < 1.0:
                return f"{name}.availability must be in (0, 1) " \
                       f"(1.0 leaves a zero error budget)"
        if "window_s" in spec:
            try:
                if float(spec["window_s"]) <= 0:
                    return f"{name}.window_s must be > 0"
            except (TypeError, ValueError):
                return f"{name}.window_s={spec['window_s']!r} is " \
                       f"not a number"
    return None


def qos_spec_error(value) -> str | None:
    """Why a ``qos`` parameter value is malformed, or None -- the
    jax-free validation twin the ``bad-parameter`` lint rule runs at
    create time, so a typo'd tenant block fails pre-flight instead of
    under load (satellite: malformed tenant/QoS blocks are create-time
    errors)."""
    if isinstance(value, str):
        try:
            value = json.loads(value)
        except json.JSONDecodeError as error:
            return f"unparseable JSON ({error})"
    if not isinstance(value, dict):
        return f"expected a dict, got {type(value).__name__}"
    unknown = set(value) - _SPEC_KEYS
    if unknown:
        return f"unknown keys {sorted(unknown)} (one of " \
               f"{sorted(_SPEC_KEYS)})"
    classes = value.get("classes", {})
    if not isinstance(classes, dict):
        return f"classes must be a dict, got {type(classes).__name__}"
    for name, spec in classes.items():
        if not isinstance(spec, dict):
            return f"classes.{name} must be a dict"
        bad = set(spec) - _CLASS_KEYS
        if bad:
            return f"classes.{name}: unknown keys {sorted(bad)}"
        try:
            weight = float(spec.get("weight", 1.0))
        except (TypeError, ValueError):
            return f"classes.{name}.weight={spec.get('weight')!r} is " \
                   f"not a number"
        if weight <= 0:
            return f"classes.{name}.weight must be > 0"
        inflight = spec.get("device_inflight")
        if inflight is not None:
            try:
                if int(inflight) < 1:
                    return f"classes.{name}.device_inflight must be >= 1"
            except (TypeError, ValueError):
                return f"classes.{name}.device_inflight=" \
                       f"{inflight!r} is not an integer"
    known = set(classes) | set(QOS_CLASSES)
    tenants = value.get("tenants", {})
    if not isinstance(tenants, dict):
        return f"tenants must be a dict, got {type(tenants).__name__}"
    entries = dict(tenants)
    if "default_tenant" in value:
        entries["default_tenant"] = value["default_tenant"]
    for name, spec in entries.items():
        if not isinstance(spec, dict):
            return f"tenants.{name} must be a dict"
        bad = set(spec) - _TENANT_KEYS
        if bad:
            return f"tenants.{name}: unknown keys {sorted(bad)}"
        for key in ("rate", "burst", "budget"):
            if key in spec:
                try:
                    if float(spec[key]) < 0:
                        return f"tenants.{name}.{key} must be >= 0"
                except (TypeError, ValueError):
                    return f"tenants.{name}.{key}={spec[key]!r} is " \
                           f"not a number"
        cls = spec.get("class")
        if cls is not None and str(cls) not in known:
            return f"tenants.{name}.class={cls!r}: one of " \
                   f"{sorted(known)}"
    for key, minimum in (("promote_ms", 0), ("age_ms", 0),
                         ("max_inflight", 0), ("session_window", 1)):
        if key in value:
            try:
                if float(value[key]) < minimum:
                    return f"{key} must be >= {minimum}"
            except (TypeError, ValueError):
                return f"{key}={value[key]!r} is not a number"
    if "slo" in value:
        problem = slo_spec_error(value["slo"])
        if problem is not None:
            return f"slo: {problem}"
        for name in value["slo"]:
            if str(name) not in known:
                return f"slo.{name}: not a declared class (one of " \
                       f"{sorted(known)})"
    return None


class SloTracker:
    """Windowed per-tenant/class error-budget burn rates from declared
    objectives (``slo: {class: {p99_ms, availability}}`` in the qos
    block).  Burn rate = (observed bad fraction) / (budgeted bad
    fraction): > 1 means the error budget is being spent faster than
    the objective allows (Vortex, PAPERS.md: per-class SLO tracking at
    the front door).  The gateway feeds it one observation per
    delivered result (+ one per front-door reject); everything here is
    jax-free, bounded, and thread-safe (gateway pump + HTTP threads).

    - latency burn: fraction of windowed samples over ``p99_ms``,
      against the 1% budget a p99 target implies.
    - availability burn: fraction of windowed samples that failed
      (error results, sheds, rejects, deadline misses), against the
      ``1 - availability`` budget.
    - overall burn = max of the declared ones.
    """

    def __init__(self, spec: dict | str | None):
        if isinstance(spec, str):
            spec = json.loads(spec) if spec else {}
        spec = dict(spec or {})
        problem = slo_spec_error(spec)
        if problem is not None:
            raise ValueError(f"slo: {problem}")
        self.objectives: dict[str, dict] = {}
        for name, entry in spec.items():
            self.objectives[str(name)] = {
                "p99_ms": (None if "p99_ms" not in entry
                           else float(entry["p99_ms"])),
                "availability": (None if "availability" not in entry
                                 else float(entry["availability"])),
                "window_s": float(entry.get("window_s",
                                            SLO_WINDOW_S_DEFAULT))}
        self._lock = threading.Lock()
        #: (tenant, cls) -> list of (monotonic stamp, e2e_ms|None, ok)
        self._samples: dict[tuple, list] = {}
        self._fired_at: dict[tuple, float] = {}
        self.fired = 0

    def tracks(self, qos_class: str | None) -> bool:
        return str(qos_class or DEFAULT_CLASS) in self.objectives

    def _window(self, qos_class: str) -> float:
        entry = self.objectives.get(qos_class)
        return SLO_WINDOW_S_DEFAULT if entry is None \
            else entry["window_s"]

    def observe(self, tenant: str | None, qos_class: str | None,
                e2e_ms: float | None, ok: bool,
                now: float | None = None) -> None:
        """One delivered result (``e2e_ms`` door-to-door) or one
        latency-less bad event (reject/shed: ``e2e_ms=None``,
        ``ok=False``)."""
        qos_class = str(qos_class or DEFAULT_CLASS)
        if qos_class not in self.objectives:
            return
        now = time.monotonic() if now is None else now
        key = (str(tenant or DEFAULT_TENANT), qos_class)
        horizon = now - self._window(qos_class)
        with self._lock:
            samples = self._samples.setdefault(key, [])
            samples.append((now, e2e_ms, bool(ok)))
            while samples and samples[0][0] < horizon:
                samples.pop(0)

    def _burn_locked(self, key: tuple, now: float) -> dict | None:
        tenant, qos_class = key
        objective = self.objectives[qos_class]
        horizon = now - objective["window_s"]
        samples = [entry for entry in self._samples.get(key, ())
                   if entry[0] >= horizon]
        if not samples:
            return None
        result = {"tenant": tenant, "cls": qos_class,
                  "samples": len(samples),
                  "window_s": objective["window_s"], "burn": 0.0}
        p99_ms = objective["p99_ms"]
        if p99_ms is not None:
            timed = [entry for entry in samples
                     if entry[1] is not None]
            over = sum(1 for entry in timed if entry[1] > p99_ms
                       or not entry[2])
            result["p99_ms_target"] = p99_ms
            result["latency_burn"] = round(
                (over / len(timed)) / 0.01, 3) if timed else 0.0
            result["burn"] = max(result["burn"],
                                 result["latency_burn"])
        availability = objective["availability"]
        if availability is not None:
            bad = sum(1 for entry in samples if not entry[2])
            result["availability_target"] = availability
            result["availability_burn"] = round(
                (bad / len(samples)) / (1.0 - availability), 3)
            result["burn"] = max(result["burn"],
                                 result["availability_burn"])
        result["burn"] = round(result["burn"], 3)
        return result

    def burn_rates(self, now: float | None = None) -> dict:
        """{tenant: {cls: burn report}} over each class's window."""
        now = time.monotonic() if now is None else now
        report: dict = {}
        with self._lock:
            for key in list(self._samples):
                entry = self._burn_locked(key, now)
                if entry is not None:
                    report.setdefault(key[0], {})[key[1]] = entry
        return report

    def fast_burns(self, now: float | None = None) -> list:
        """Newly-firing (tenant, cls, burn) triples with burn > 1,
        debounced :data:`SLO_FIRE_COOLDOWN_S` per key -- the
        remediation trigger (ring event + black-box dump; ROADMAP
        item 4's controller subscribes to exactly this)."""
        now = time.monotonic() if now is None else now
        fired = []
        with self._lock:
            for key in list(self._samples):
                entry = self._burn_locked(key, now)
                if entry is None or entry["burn"] <= 1.0:
                    continue
                last = self._fired_at.get(key, -1e9)
                if now - last < SLO_FIRE_COOLDOWN_S:
                    continue
                self._fired_at[key] = now
                self.fired += 1
                fired.append((key[0], key[1], entry["burn"]))
        return fired

    def snapshot(self, now: float | None = None) -> dict:
        return {"objectives": {name: dict(entry) for name, entry
                               in self.objectives.items()},
                "fired": self.fired,
                "tenants": self.burn_rates(now)}


class QosScheduler:
    """The one admission authority.  Holds no references into the
    engine: the planes call in with frames/classes and get ranks and
    verdicts back, so it stays unit-testable and import-cycle-free.

    Thread-safety: rank/class lookups are read-only after construction
    (safe everywhere); the mutable tenant counters (inflight,
    admit/reject/shed) are guarded by one lock because the gateway's
    connection threads and the engine loop both touch them."""

    def __init__(self, spec: dict | str | None = None):
        spec = spec or {}
        if isinstance(spec, str):
            spec = json.loads(spec)
        problem = qos_spec_error(spec)
        if problem is not None:
            raise ValueError(f"qos: {problem}")
        weights = dict(_DEFAULT_WEIGHTS)
        class_specs: dict[str, dict] = {name: {} for name in QOS_CLASSES}
        for name, entry in (spec.get("classes") or {}).items():
            class_specs.setdefault(str(name), {}).update(entry)
            if "weight" in entry:
                weights[str(name)] = float(entry["weight"])
            weights.setdefault(str(name), 1.0)
        #: class name -> rank (0 = most urgent), by descending weight;
        #: name breaks weight ties deterministically.
        ordered = sorted(class_specs,
                         key=lambda name: (-weights.get(name, 1.0), name))
        self.class_ranks: dict[str, int] = {
            name: rank for rank, name in enumerate(ordered)}
        self.classes = tuple(ordered)
        self._class_specs = class_specs
        self.promote_ms = float(spec.get("promote_ms",
                                         PROMOTE_MS_DEFAULT))
        self.age_ms = float(spec.get("age_ms", AGE_MS_DEFAULT))
        self.max_inflight = int(spec.get("max_inflight", 0))
        self.session_window = int(spec.get("session_window", 32))
        self._default_tenant_spec = dict(spec.get("default_tenant")
                                         or {})
        self._lock = threading.Lock()
        self.tenants: dict[str, _Tenant] = {}
        for name, tenant_spec in (spec.get("tenants") or {}).items():
            self.tenants[str(name)] = _Tenant(str(name), tenant_spec)
        self._configured_tenants = len(self.tenants)
        self._seq = 0
        self.promotions = 0
        self.inflight_total = 0
        #: declared objectives -> burn-rate tracker (None without an
        #: ``slo`` block); the gateway feeds it per delivered result.
        self.slo = SloTracker(spec["slo"]) if spec.get("slo") else None

    # -- resolution --------------------------------------------------------

    def resolve_class(self, name, tenant: str | None = None) -> str:
        """A stream/request's class: explicit name when known, else
        the tenant's default (falling back to the ``default_tenant``
        spec's class when the lazy entry doesn't exist yet -- the
        FIRST session of an unlisted tenant must resolve exactly like
        its second), else ``standard``."""
        if name is not None and str(name) in self.class_ranks:
            return str(name)
        entry = self.tenants.get(str(tenant or ""))
        if entry is not None \
                and entry.default_class in self.class_ranks:
            return entry.default_class
        if entry is None:
            fallback = str(self._default_tenant_spec.get("class", ""))
            if fallback in self.class_ranks:
                return fallback
        return DEFAULT_CLASS

    def tenant(self, name: str | None) -> _Tenant:
        """The tenant's resolved state, lazily created from
        ``default_tenant`` for names with no explicit block (a
        multi-tenant gateway must not require pre-registering every
        tenant -- the default block IS the policy for the long tail).
        Lazy creation is bounded at :data:`LAZY_TENANT_CAP`: past it,
        unknown names share the default entry rather than growing
        scheduler state and metric cardinality without bound."""
        key = str(name or DEFAULT_TENANT)
        with self._lock:
            entry = self.tenants.get(key)
            if entry is None:
                if len(self.tenants) >= self._configured_tenants \
                        + LAZY_TENANT_CAP:
                    entry = self.tenants.get(DEFAULT_TENANT)
                    if entry is None:
                        entry = self.tenants[DEFAULT_TENANT] = _Tenant(
                            DEFAULT_TENANT, self._default_tenant_spec)
                    return entry
                entry = self.tenants[key] = _Tenant(
                    key, self._default_tenant_spec)
            return entry

    def class_rank(self, name: str | None) -> int:
        return self.class_ranks.get(str(name or DEFAULT_CLASS),
                                    self.class_ranks.get(DEFAULT_CLASS,
                                                         0))

    def next_seq(self) -> int:
        """Global ingest sequence: the rank tiebreak that preserves
        arrival (and per-stream) order within a class."""
        with self._lock:
            self._seq += 1
            return self._seq

    # -- the four planes ---------------------------------------------------

    def rank_frame(self, frame, now: float | None = None) -> tuple:
        """Sort key for a waiting frame, used by every queue pop: the
        StageScheduler waiter queues and the pipeline-wide shed
        victim walk.  (effective class rank, ingest seq) -- promotion
        near deadline lifts to rank 0, aging subtracts one class step
        per ``age_ms`` waited."""
        now = time.monotonic() if now is None else now
        rank = self.class_rank(getattr(frame, "qos_class", None))
        promoted = False
        deadline = getattr(frame, "deadline", None)
        if deadline is not None and rank > 0 and self.promote_ms > 0 \
                and (deadline - now) * 1000.0 <= self.promote_ms:
            rank = 0
            promoted = True
        enqueued = getattr(frame, "qos_wait_start", None)
        if not promoted and rank > 0 and self.age_ms > 0 \
                and enqueued is not None:
            rank = max(0, rank - int((now - enqueued) * 1000.0
                                     // self.age_ms))
        if promoted and not getattr(frame, "qos_promoted", False):
            frame.qos_promoted = True
            with self._lock:
                self.promotions += 1
        return rank, getattr(frame, "qos_seq", 0)

    def device_limit(self, qos_class: str | None, base: int) -> int:
        """Plane 1 -- DeviceWindow pacing: a class may declare its own
        ``device_inflight`` cap (e.g. batch double-buffers while
        interactive keeps the full window).  Without one the stream's
        resolved limit stands; 0/negative base means pacing is off and
        the class cap (if any) becomes the bound."""
        spec = self._class_specs.get(str(qos_class or DEFAULT_CLASS))
        cap = None if spec is None else spec.get("device_inflight")
        if cap is None:
            return base
        cap = int(cap)
        return cap if base is None or base <= 0 else min(base, cap)

    def latency_sensitive(self, qos_class: str | None) -> bool:
        """Plane 3 -- ReplicaGroup slot pick: rank-0 classes pick the
        least-loaded live replica (head-of-line latency) instead of
        round-robin (throughput fairness)."""
        return self.class_rank(qos_class) == 0

    # -- gateway admission + budgets ---------------------------------------

    def admit(self, tenant_name: str | None,
              qos_class: str | None = None) -> tuple[bool, str]:
        """Front-door admission for one frame: (admitted, reason).
        Only the token bucket rejects here -- budget overruns shed
        later (under actual overload) rather than rejecting eagerly,
        so an over-budget tenant still gets service when the engine
        has headroom."""
        entry = self.tenant(tenant_name)
        if not entry.bucket.take():
            with self._lock:
                entry.rejected += 1
            return False, "rate"
        with self._lock:
            entry.admitted += 1
        return True, ""

    def frame_started(self, tenant_name: str | None) -> None:
        entry = self.tenant(tenant_name)
        with self._lock:
            entry.inflight += 1
            self.inflight_total += 1

    def frame_finished(self, tenant_name: str | None) -> None:
        entry = self.tenant(tenant_name)
        with self._lock:
            entry.inflight = max(0, entry.inflight - 1)
            self.inflight_total = max(0, self.inflight_total - 1)

    def count_shed(self, tenant_name: str | None) -> None:
        entry = self.tenant(tenant_name)
        with self._lock:
            entry.shed += 1

    def overloaded(self) -> bool:
        """Pipeline-wide in-flight cap (``max_inflight``; 0 = off) --
        the trigger for qos-ranked shedding across ALL streams, where
        the per-stream ``overload_limit`` cannot express "batch
        absorbs the shedding"."""
        return self.max_inflight > 0 \
            and self.inflight_total >= self.max_inflight

    def budget_snapshot(self) -> dict:
        """{tenant: over_budget} in ONE locked pass -- the shed walk
        ranks every queued frame against this snapshot instead of
        taking the scheduler lock per candidate (an overloaded ingest
        scans up to ``max_inflight`` frames on the event loop, exactly
        when the gateway threads contend hardest)."""
        with self._lock:
            return {name: entry.over_budget
                    for name, entry in self.tenants.items()}

    def shed_key(self, frame, budgets: dict | None = None) -> tuple:
        """Victim ordering under overload: BIGGEST key sheds first --
        over-budget tenants, then the lowest class, then the oldest
        frame (its deadline is nearest to being missed anyway).
        ``budgets`` is a :meth:`budget_snapshot` (pass one when
        ranking many frames); absent, the live entry is consulted."""
        name = getattr(frame, "tenant", None)
        if budgets is not None:
            over = budgets.get(str(name or DEFAULT_TENANT), False)
        else:
            over = self.tenant(name).over_budget
        return (1 if over else 0,
                self.class_rank(getattr(frame, "qos_class", None)),
                -getattr(frame, "qos_seq", 0))

    # -- reporting ---------------------------------------------------------

    def stats(self) -> dict:
        slo = None if self.slo is None else self.slo.snapshot()
        with self._lock:
            return {
                "slo": slo,
                "classes": {name: rank for name, rank
                            in self.class_ranks.items()},
                "promote_ms": self.promote_ms,
                "age_ms": self.age_ms,
                "max_inflight": self.max_inflight,
                "inflight_total": self.inflight_total,
                "promotions": self.promotions,
                "tenants": {
                    name: {"inflight": entry.inflight,
                           "budget": entry.budget,
                           "over_budget": entry.over_budget,
                           "admitted": entry.admitted,
                           "rejected": entry.rejected,
                           "shed": entry.shed,
                           "class": entry.default_class}
                    for name, entry in self.tenants.items()}}

    @staticmethod
    def parse(spec) -> "QosScheduler | None":
        """``qos`` pipeline-parameter value -> scheduler (None when
        absent/falsy); raises ValueError with the qos_spec_error
        diagnostic on malformed input (the ``preflight: off`` escape
        hatch must not smuggle a bad block past create)."""
        if not spec:
            return None
        return QosScheduler(spec)
