"""Minimal RFC 6455 WebSocket codec (stdlib only).

The gateway's session protocol needs exactly the core of the RFC:
the HTTP/1.1 upgrade handshake (client and server sides), text/binary
data frames with client-side masking, and the ping/pong/close control
opcodes.  No extensions, no compression, no fragmentation on send
(every frame is FIN); fragmented receives are reassembled.  Both the
server (gateway/server.py) and the in-tree client
(gateway/client.py, used by the load generator and tier-1 tests over
loopback) speak through these functions, so the protocol surface has
one implementation.
"""

from __future__ import annotations

import base64
import hashlib
import os
import socket
import struct

__all__ = ["OP_TEXT", "OP_BINARY", "OP_CLOSE", "OP_PING", "OP_PONG",
           "accept_key", "client_handshake", "server_handshake",
           "send_frame", "recv_frame", "recv_message", "WsClosed"]

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT = 0x0
OP_TEXT = 0x1
OP_BINARY = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA


class WsClosed(Exception):
    """The peer closed the connection (close frame or EOF)."""


def accept_key(key: str) -> str:
    digest = hashlib.sha1((key + _GUID).encode()).digest()
    return base64.b64encode(digest).decode()


def client_handshake(sock: socket.socket, host: str, port: int,
                     path: str = "/v1/stream") -> None:
    """Send the upgrade request and validate the 101 response.
    Raises ConnectionError on anything but a correct accept."""
    key = base64.b64encode(os.urandom(16)).decode()
    request = (f"GET {path} HTTP/1.1\r\n"
               f"Host: {host}:{port}\r\n"
               "Upgrade: websocket\r\n"
               "Connection: Upgrade\r\n"
               f"Sec-WebSocket-Key: {key}\r\n"
               "Sec-WebSocket-Version: 13\r\n\r\n")
    sock.sendall(request.encode())
    reply = _read_head(sock)
    status = reply.split("\r\n", 1)[0]
    if " 101 " not in f"{status} ":
        raise ConnectionError(f"websocket upgrade refused: {status}")
    expected = accept_key(key)
    for line in reply.split("\r\n")[1:]:
        name, _, value = line.partition(":")
        if name.strip().lower() == "sec-websocket-accept" \
                and value.strip() == expected:
            return
    raise ConnectionError("websocket upgrade: bad Sec-WebSocket-Accept")


def server_handshake(headers: dict) -> bytes | None:
    """The 101 response bytes for an upgrade request's headers
    (lower-cased names), or None when this is not a websocket
    upgrade."""
    if "websocket" not in str(headers.get("upgrade", "")).lower():
        return None
    key = headers.get("sec-websocket-key")
    if not key:
        return None
    return ("HTTP/1.1 101 Switching Protocols\r\n"
            "Upgrade: websocket\r\n"
            "Connection: Upgrade\r\n"
            f"Sec-WebSocket-Accept: {accept_key(str(key).strip())}"
            "\r\n\r\n").encode()


def _read_head(sock: socket.socket) -> str:
    """Read up to the blank line ending an HTTP head."""
    data = b""
    while b"\r\n\r\n" not in data:
        chunk = sock.recv(4096)
        if not chunk:
            raise ConnectionError("connection closed during handshake")
        data += chunk
        if len(data) > 65536:
            raise ConnectionError("oversized handshake")
    return data.split(b"\r\n\r\n", 1)[0].decode("latin-1")


def send_frame(sock: socket.socket, payload: bytes | str,
               opcode: int | None = None, mask: bool = False) -> None:
    """One FIN frame.  Clients MUST mask (RFC 6455 §5.3); servers must
    not."""
    if isinstance(payload, str):
        payload = payload.encode()
        opcode = OP_TEXT if opcode is None else opcode
    else:
        opcode = OP_BINARY if opcode is None else opcode
    head = bytes([0x80 | opcode])
    length = len(payload)
    mask_bit = 0x80 if mask else 0
    if length < 126:
        head += bytes([mask_bit | length])
    elif length < 65536:
        head += bytes([mask_bit | 126]) + struct.pack(">H", length)
    else:
        head += bytes([mask_bit | 127]) + struct.pack(">Q", length)
    if mask:
        key = os.urandom(4)
        body = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
        sock.sendall(head + key + body)
    else:
        sock.sendall(head + payload)


def _read_exact(sock: socket.socket, count: int) -> bytes:
    data = b""
    while len(data) < count:
        chunk = sock.recv(count - len(data))
        if not chunk:
            raise WsClosed("connection closed mid-frame")
        data += chunk
    return data


#: default bound on one received frame AND one reassembled message --
#: the unauthenticated front door must not buffer an attacker-chosen
#: 64-bit length (or endless continuation fragments) into RAM before
#: any admission check runs.  Raising past it is a protocol violation:
#: the connection dies (WsClosed), never the process.
MAX_PAYLOAD_DEFAULT = 64 << 20


def recv_frame(sock: socket.socket,
               max_payload: int = MAX_PAYLOAD_DEFAULT) \
        -> tuple[int, bool, bytes]:
    """One wire frame -> (opcode, fin, unmasked payload)."""
    head = _read_exact(sock, 2)
    fin = bool(head[0] & 0x80)
    opcode = head[0] & 0x0F
    masked = bool(head[1] & 0x80)
    length = head[1] & 0x7F
    if length == 126:
        length = struct.unpack(">H", _read_exact(sock, 2))[0]
    elif length == 127:
        length = struct.unpack(">Q", _read_exact(sock, 8))[0]
    if max_payload and length > max_payload:
        raise WsClosed(f"frame of {length} bytes exceeds the "
                       f"{max_payload}-byte bound")
    key = _read_exact(sock, 4) if masked else None
    payload = _read_exact(sock, length) if length else b""
    if key is not None:
        payload = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
    return opcode, fin, payload


def recv_message(sock: socket.socket,
                 respond_control: bool = True,
                 mask_replies: bool = False,
                 max_payload: int = MAX_PAYLOAD_DEFAULT,
                 on_frame=None) -> tuple[int, bytes]:
    """The next DATA message (text/binary), reassembling continuation
    frames and answering pings in line.  Raises :class:`WsClosed` on a
    close frame, EOF, or a frame/message past ``max_payload``.
    ``on_frame(opcode)`` fires for every wire frame received --
    control frames included, which is how the gateway's idle-session
    reaper sees a client's pong as liveness."""
    opcode, payload = None, b""
    while True:
        frame_op, fin, chunk = recv_frame(sock, max_payload=max_payload)
        if on_frame is not None:
            on_frame(frame_op)
        if frame_op == OP_CLOSE:
            if respond_control:
                try:
                    send_frame(sock, chunk, OP_CLOSE,
                               mask=mask_replies)
                except OSError:
                    pass
            raise WsClosed("close frame")
        if frame_op == OP_PING:
            if respond_control:
                send_frame(sock, chunk, OP_PONG, mask=mask_replies)
            continue
        if frame_op == OP_PONG:
            continue
        if frame_op in (OP_TEXT, OP_BINARY):
            opcode = frame_op
        elif frame_op != OP_CONT or opcode is None:
            raise WsClosed(f"unexpected opcode {frame_op}")
        payload += chunk
        if max_payload and len(payload) > max_payload:
            # continuation fragments must not sidestep the per-frame
            # bound by arriving small and endless
            raise WsClosed(f"message exceeds the {max_payload}-byte "
                           f"bound")
        if fin:
            return opcode, payload
