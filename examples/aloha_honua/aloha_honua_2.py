#!/usr/bin/env python3
"""Request/response: ask the actor a question and collect the replies
(reference: examples/aloha_honua/aloha_honua_3.py:41-98 do_request).

Run::

    python examples/aloha_honua/aloha_honua_2.py
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from aiko_services_tpu.runtime import init_process
from aiko_services_tpu.services import (Actor, Registrar, ServiceFilter,
                                        do_request)
from aiko_services_tpu.utils import generate


class AlohaHonua(Actor):
    def __init__(self, name="aloha_honua", runtime=None):
        super().__init__(name, "aloha_honua:0", runtime=runtime)

    def inquiry(self, response_topic, question):
        publish = self.runtime.message.publish
        publish(response_topic, generate("item_count", [2]))
        publish(response_topic, generate("response", [question, "aloha"]))
        publish(response_topic, generate("response", [question, "honua"]))


def main():
    runtime = init_process(transport="loopback")
    runtime.initialize()
    Registrar(runtime=runtime, primary_search_timeout=0.1)
    AlohaHonua(runtime=runtime)

    def on_responses(items):
        for command, parameters in items:
            print(f"response: {parameters}")
        runtime.engine.add_oneshot_timer(runtime.terminate, 0.2)

    do_request(runtime, None, ServiceFilter(protocol="aloha_honua"),
               lambda proxy, topic: proxy.inquiry(topic, "greeting"),
               on_responses)
    runtime.run(timeout=10.0)


if __name__ == "__main__":
    main()
