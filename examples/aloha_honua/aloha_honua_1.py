#!/usr/bin/env python3
"""Discovery + do_command: find the actor through the Registrar and call
it by proxy (reference: examples/aloha_honua/aloha_honua_1.py:40-48).

Run::

    python examples/aloha_honua/aloha_honua_1.py
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from aiko_services_tpu.runtime import init_process
from aiko_services_tpu.services import (Actor, Registrar, ServiceFilter,
                                        do_command)


class AlohaHonua(Actor):
    def __init__(self, name="aloha_honua", runtime=None):
        super().__init__(name, "aloha_honua:0", runtime=runtime)
        self.greeted = []

    def aloha(self, name):
        self.greeted.append(name)
        print(f"Aloha {name}!")
        if len(self.greeted) >= 1:
            self.runtime.engine.add_oneshot_timer(
                self.runtime.terminate, 0.2)


def main():
    runtime = init_process(transport="loopback")
    runtime.initialize()
    Registrar(runtime=runtime, primary_search_timeout=0.1)
    AlohaHonua(runtime=runtime)

    # No topic paths anywhere: the caller only knows the protocol.
    do_command(runtime, None, ServiceFilter(protocol="aloha_honua"),
               lambda proxy: proxy.aloha("Honua"))
    runtime.run(timeout=10.0)


if __name__ == "__main__":
    main()
