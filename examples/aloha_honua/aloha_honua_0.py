#!/usr/bin/env python3
"""Minimal Actor: say aloha (reference:
examples/aloha_honua/aloha_honua_0.py:34-45).

Run (no broker needed)::

    python examples/aloha_honua/aloha_honua_0.py

A remote caller (or this script itself, below) publishes
``(aloha Pele)`` to the actor's ``topic/in`` and the method runs on the
actor's mailbox.
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from aiko_services_tpu.runtime import init_process
from aiko_services_tpu.services import Actor


class AlohaHonua(Actor):
    def __init__(self, name="aloha_honua", runtime=None):
        super().__init__(name, "aloha_honua:0", runtime=runtime)

    def aloha(self, name):
        self.logger.info(f"Aloha {name}!")
        print(f"Aloha {name}!")


def main():
    runtime = init_process(transport="loopback")
    runtime.initialize()
    actor = AlohaHonua(runtime=runtime)

    # Message the actor over the fabric, then stop after it's handled.
    runtime.message.publish(f"{actor.topic_path}/in", "(aloha Pele)")
    runtime.engine.add_oneshot_timer(runtime.terminate, 0.5)
    runtime.run()


if __name__ == "__main__":
    main()
