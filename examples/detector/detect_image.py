#!/usr/bin/env python3
"""Detection pipeline: read image -> JAX detector -> draw overlays ->
write image (reference: examples/yolo/yolo.py YoloDetector + ImageOverlay
on torch/CUDA; here the detector is the framework's own JAX model with
weights in HBM -- BASELINE config 2).

    python examples/detector/detect_image.py [input.png [output.png]]

Without arguments a synthetic test image is generated first.
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

import queue

import numpy as np

from aiko_services_tpu.pipeline import Pipeline
from aiko_services_tpu.runtime import init_process


def definition(in_path, out_path):
    def el(name, cls, inputs, outputs, parameters=None, module=None):
        return {"name": name,
                "input": [{"name": n} for n in inputs],
                "output": [{"name": n} for n in outputs],
                "parameters": parameters or {},
                "deploy": {"local": {
                    "module": module or "aiko_services_tpu.elements",
                    "class_name": cls}}}
    return {
        "version": 0, "name": "detect_demo", "runtime": "jax",
        "graph": ["(read detect overlay write)"],
        "elements": [
            el("read", "ImageReadFile", ["path"], ["image"],
               {"data_sources": [f"file://{in_path}"]}),
            el("detect", "Detector", ["image"],
               ["image", "overlay", "detections"],
               {"score_threshold": 0.0}),     # random weights: show boxes
            el("overlay", "ImageOverlay", ["image", "overlay"], ["image"]),
            el("write", "ImageWriteFile", ["image"], [],
               {"data_targets": [f"file://{out_path}"]}),
        ]}


def main():
    in_path = sys.argv[1] if len(sys.argv) > 1 else "/tmp/detect_in.png"
    out_path = sys.argv[2] if len(sys.argv) > 2 else "/tmp/detect_out.png"
    if len(sys.argv) <= 1:
        from PIL import Image
        rng = np.random.default_rng(0)
        Image.fromarray(rng.integers(0, 255, (96, 128, 3),
                                     dtype=np.uint8)).save(in_path)
        print(f"wrote synthetic input {in_path}")

    runtime = init_process(transport="loopback")
    runtime.initialize()
    pipeline = Pipeline(definition(in_path, out_path), runtime=runtime)
    responses = queue.Queue()
    pipeline.create_stream_local("1", queue_response=responses)
    runtime.run(until=lambda: not responses.empty(), timeout=120.0)
    _, _, swag, metrics, okay, diagnostic = responses.get()
    assert okay, diagnostic
    print(f"detections: {len(swag.get('detections', []))}, "
          f"detector time {metrics.get('detect_time', 0) * 1e3:.1f} ms, "
          f"output {out_path}")
    runtime.terminate()


if __name__ == "__main__":
    main()
