#!/usr/bin/env python3
"""Chat against an in-process LLMService (reference: examples/llm/
elements.py LLM element backed by an external Ollama server; here the
model is native JAX with continuous batching -- see
aiko_services_tpu/elements/llm.py).

    python examples/llm/chat.py "your prompt" [more prompts ...]

All prompts decode CONCURRENTLY through one batched KV cache; token
streams interleave on the wire.  With random tiny weights the output is
gibberish bytes -- pass ``checkpoint=<orbax dir>`` via LLMService for a
trained model.
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from aiko_services_tpu.elements import LLMService
from aiko_services_tpu.runtime import init_process
from aiko_services_tpu.services import get_service_proxy
from aiko_services_tpu.utils import parse


def main():
    prompts = sys.argv[1:] or ["aloha", "honua"]
    runtime = init_process(transport="loopback")
    runtime.initialize()
    service = LLMService(runtime=runtime, max_slots=max(2, len(prompts)))
    proxy = get_service_proxy(runtime, service.topic_path)

    pending = set()
    response_topic = f"{runtime.topic_path_process}/chat"

    def on_reply(topic, payload):
        command, parameters = parse(payload)
        if command == "token":
            print(f"[{parameters[0]}] +{parameters[1]!r}")
        elif command == "complete":
            print(f"[{parameters[0]}] DONE: {parameters[1]!r}")
            pending.discard(parameters[0])

    runtime.add_message_handler(on_reply, response_topic)
    for index, prompt in enumerate(prompts):
        request_id = f"req{index}"
        pending.add(request_id)
        proxy.generate(response_topic, request_id, prompt, 12, 0)

    runtime.run(until=lambda: not pending, timeout=120.0)
    runtime.terminate()


if __name__ == "__main__":
    main()
