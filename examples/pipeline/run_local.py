#!/usr/bin/env python3
"""Run the fan-out/fan-in diamond pipeline and print each frame's result
(reference: aiko_pipeline create pipeline_local.json).

    python examples/pipeline/run_local.py
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

import os
import queue

from aiko_services_tpu.pipeline import create_pipeline
from aiko_services_tpu.runtime import init_process


def main():
    os.chdir(os.path.join(os.path.dirname(__file__), "..", ".."))
    runtime = init_process(transport="loopback")
    runtime.initialize()
    pipeline = create_pipeline("examples/pipeline/pipeline_local.json",
                               runtime=runtime)
    responses = queue.Queue()
    pipeline.create_stream_local("1", queue_response=responses)

    done = 0
    while done < 5:
        runtime.run(until=lambda: not responses.empty(), timeout=10.0)
        if responses.empty():
            break
        _, frame_id, swag, metrics, okay, _ = responses.get()
        print(f"frame {frame_id}: x={swag['x']} -> "
              f"double={swag['y']} square={swag['z']} "
              f"result={swag['result']} "
              f"({metrics['time_pipeline'] * 1e3:.2f} ms)")
        done += 1
    runtime.terminate()


if __name__ == "__main__":
    main()
