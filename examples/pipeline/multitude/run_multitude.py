#!/usr/bin/env python3
"""Multitude scale test: N chained pipelines, frames flowing front-to-back
through remote stages (reference: examples/pipeline/multitude/
run_small.sh / run_large.sh, which chain 3/10 pipeline processes over
mosquitto and top out near 50 frames/sec).

    python examples/pipeline/multitude/run_multitude.py [N_pipelines] [frames]

All pipelines share this process over the loopback broker (the same
definitions distribute across processes over MQTT unchanged); each stage
increments x, so a frame returning with x == N proves it traversed every
pipeline.
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..", "..")))

import queue
import sys
import time

from aiko_services_tpu.pipeline import Pipeline
from aiko_services_tpu.runtime import init_process
from aiko_services_tpu.services import Registrar


def element(name, cls, inputs, outputs, parameters=None):
    return {"name": name,
            "input": [{"name": n} for n in inputs],
            "output": [{"name": n} for n in outputs],
            "parameters": parameters or {},
            "deploy": {"local": {
                "module": "aiko_services_tpu.elements.common",
                "class_name": cls}}}


def remote(name, target, inputs, outputs):
    return {"name": name,
            "input": [{"name": n} for n in inputs],
            "output": [{"name": n} for n in outputs],
            "deploy": {"remote": {"name": target}}}


def main():
    n_pipelines = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    n_frames = int(sys.argv[2]) if len(sys.argv) > 2 else 500

    runtime = init_process(transport="loopback")
    runtime.initialize()
    Registrar(runtime=runtime, primary_search_timeout=0.1)

    # Tail pipeline first, then each one chains to the next.
    names = [f"multitude_{i}" for i in range(n_pipelines)]
    for i in reversed(range(n_pipelines)):
        elements = [element("inc", "Increment", ["x"], ["x"])]
        graph = "(inc)"
        if i < n_pipelines - 1:
            elements.append(remote("next", names[i + 1], ["x"], ["x"]))
            graph = "(inc next)"
        definition = {"version": 0, "name": names[i], "runtime": "jax",
                      "graph": [graph], "elements": elements}
        instance = Pipeline(definition, runtime=runtime)
        if i == 0:
            front = instance

    responses = queue.Queue()
    front.create_stream_local("1", queue_response=responses)

    received = [0]
    start = time.perf_counter()
    for _ in range(n_frames):
        front.ingest_local("1", {"x": 0}, queue_response=responses)

    def drained():
        while not responses.empty():
            _, _, swag, _, okay, _ = responses.get()
            assert okay and int(swag["x"]) == n_pipelines, swag
            received[0] += 1
        return received[0] >= n_frames

    runtime.run(until=drained, timeout=120.0)
    elapsed = time.perf_counter() - start
    fps = received[0] / elapsed
    print(f"{received[0]}/{n_frames} frames through {n_pipelines} chained "
          f"pipelines in {elapsed:.2f}s = {fps:.0f} frames/sec "
          f"(reference multitude ceiling: ~50 frames/sec)")
    runtime.terminate()


if __name__ == "__main__":
    main()
