"""Example PipelineElements (reference: examples/pipeline/elements.py:
39-246 -- PE_Add, PE_RandomIntegers, fan-out/fan-in PEs, data codecs)."""

from __future__ import annotations

import random

from aiko_services_tpu.pipeline import PipelineElement, StreamEvent


class RandomIntegers(PipelineElement):
    """Source: emits ``count`` random integers at ``rate`` frames/sec."""

    def start_stream(self, stream, stream_id):
        count = int(self.get_parameter("count", 10)[0])
        seed = self.get_parameter("seed", None)[0]
        rng = random.Random(int(seed)) if seed is not None else random.Random()

        emitted = {"n": 0}

        def frame_generator(stream):
            if emitted["n"] >= count:
                return StreamEvent.STOP, {"diagnostic": "all frames sent"}
            emitted["n"] += 1
            return StreamEvent.OKAY, {"x": rng.randint(0, 100)}

        rate = self.get_parameter("rate", None)[0]
        self.create_frames(stream, frame_generator,
                           float(rate) if rate else None)
        return StreamEvent.OKAY, {}

    def process_frame(self, stream, **inputs):
        return StreamEvent.OKAY, dict(inputs)


class Add(PipelineElement):
    """x -> x + constant (fan-out/fan-in demo arithmetic)."""

    def process_frame(self, stream, x):
        constant = int(self.get_parameter("constant", 1)[0])
        return StreamEvent.OKAY, {"x": int(x) + constant}


class Double(PipelineElement):
    def process_frame(self, stream, x):
        return StreamEvent.OKAY, {"y": int(x) * 2}


class Square(PipelineElement):
    def process_frame(self, stream, x):
        return StreamEvent.OKAY, {"z": int(x) * int(x)}


class Combine(PipelineElement):
    """Fan-in: merge the two branch results."""

    def process_frame(self, stream, y, z):
        return StreamEvent.OKAY, {"result": int(y) + int(z)}


class Print(PipelineElement):
    def process_frame(self, stream, **inputs):
        print(f"frame: {inputs}")
        return StreamEvent.OKAY, dict(inputs)


class Identity(PipelineElement):
    """Pass-through entry element: each named graph path gets its own
    head (path selection is by head name -- Stream.graph_path)."""

    def process_frame(self, stream, **inputs):
        return StreamEvent.OKAY, dict(inputs)


class Select(PipelineElement):
    """Multi-path sink: first non-None of its optional inputs becomes
    ``result`` (paths write different swag keys; one sink serves all)."""

    def process_frame(self, stream, y=None, z=None, x=None, **inputs):
        for value in (y, z, x):
            if value is not None:
                return StreamEvent.OKAY, {"result": value}
        return StreamEvent.OKAY, {"result": None}
