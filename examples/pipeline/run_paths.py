#!/usr/bin/env python3
"""Multi-path pipeline: one definition, three named graph paths; each
stream runs exactly one path, selected by head name (reference:
aiko_pipeline create pipeline_paths.json -s 1 -gp PE_IN_1).

    python examples/pipeline/run_paths.py
"""

import os
import queue
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from aiko_services_tpu.pipeline import create_pipeline
from aiko_services_tpu.runtime import init_process


def main():
    os.chdir(os.path.join(os.path.dirname(__file__), "..", ".."))
    runtime = init_process(transport="loopback")
    runtime.initialize()
    pipeline = create_pipeline("examples/pipeline/pipeline_paths.json",
                               runtime=runtime)
    for path, x in (("in_double", 6), ("in_square", 6), ("in_pass", 6)):
        responses = queue.Queue()
        pipeline.create_stream_local(path, graph_path=path,
                                     queue_response=responses)
        pipeline.process_frame_local({"x": x}, stream_id=path)
        runtime.run(until=lambda: not responses.empty(), timeout=10.0)
        _, _, swag, _, okay, diagnostic = responses.get()
        assert okay, diagnostic
        print(f"path {path}: x={x} -> result={swag['result']}")
    runtime.terminate()


if __name__ == "__main__":
    main()
