#!/usr/bin/env python3
"""Remote pipeline stage: 'p_front' forwards each frame to 'p_worker'
(discovered by name through the Registrar) and resumes when the worker's
outputs return -- the framework's pause/resume continuation (reference:
examples/pipeline/pipeline_remote.json + a second aiko_pipeline process).

Both pipelines run in this one process over the loopback broker; with an
MQTT broker the same two definitions run in separate processes/hosts
unchanged.

    python examples/pipeline/run_remote.py
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

import os
import queue

from aiko_services_tpu.pipeline import create_pipeline
from aiko_services_tpu.runtime import init_process
from aiko_services_tpu.services import Registrar


def main():
    os.chdir(os.path.join(os.path.dirname(__file__), "..", ".."))
    runtime = init_process(transport="loopback")
    runtime.initialize()
    Registrar(runtime=runtime, primary_search_timeout=0.1)

    create_pipeline("examples/pipeline/pipeline_worker.json",
                    runtime=runtime)
    front = create_pipeline("examples/pipeline/pipeline_remote.json",
                            runtime=runtime)

    responses = queue.Queue()
    front.create_stream_local("1", queue_response=responses)

    done = 0
    while done < 5:
        runtime.run(until=lambda: not responses.empty(), timeout=15.0)
        if responses.empty():
            break
        _, frame_id, swag, _, okay, diagnostic = responses.get()
        print(f"frame {frame_id}: x={swag['x']} (worker added 100) "
              f"okay={okay}")
        done += 1
    runtime.terminate()


if __name__ == "__main__":
    main()
