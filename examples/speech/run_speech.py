#!/usr/bin/env python3
"""Voice round trip: WAV in -> ASR -> LLM -> TTS -> WAV out.

The TPU-native counterpart of the reference's speech pipelines
(examples/speech/*.json: microphone -> WhisperX STT -> LLM -> Coqui TTS
-> speaker).  File endpoints stand in for mic/speaker here so the demo
runs anywhere; swap the read element for ``MicrophoneRead``
(mic:// scheme) and the write element for ``SpeakerWrite`` on a machine
with sound hardware.

    python examples/speech/run_speech.py
"""

import os
import queue
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

import json
import tempfile

import numpy as np

from aiko_services_tpu.elements.audio import write_wav
from aiko_services_tpu.pipeline import create_pipeline
from aiko_services_tpu.runtime import init_process


def main():
    here = os.path.dirname(os.path.abspath(__file__))
    workdir = tempfile.mkdtemp(prefix="speech_demo_")
    input_wav = os.path.join(workdir, "input.wav")
    reply_wav = os.path.join(workdir, "reply.wav")

    # Fabricate an utterance: 0.5 s of band-limited noise at 16 kHz
    # (stands in for recorded speech; a fitted ASR checkpoint would be
    # pointed at real audio).
    rng = np.random.default_rng(0)
    samples = rng.standard_normal(8000).astype(np.float32) * 0.1
    write_wav(input_wav, samples, 16000)

    # Re-point the definition's file endpoints at the temp dir.
    with open(os.path.join(here, "pipeline_speech.json")) as fh:
        spec = json.load(fh)
    for entry in spec["elements"]:
        if entry["name"] == "read":
            entry["parameters"]["data_sources"] = f"file://{input_wav}"
        if entry["name"] == "write":
            entry["parameters"]["data_targets"] = f"file://{reply_wav}"
    definition_path = os.path.join(workdir, "pipeline_speech.json")
    with open(definition_path, "w") as fh:
        json.dump(spec, fh)

    runtime = init_process(transport="loopback")
    runtime.initialize()
    pipeline = create_pipeline(definition_path, runtime=runtime)
    responses = queue.Queue()
    pipeline.create_stream_local("1", queue_response=responses)
    runtime.run(until=lambda: not responses.empty(), timeout=120.0)
    if responses.empty():
        print("pipeline produced no response within 120 s")
        return 1

    _, _, swag, metrics, okay, diagnostic = responses.get()
    if not okay:
        print(f"pipeline error: {diagnostic}")
        return 1
    print(f"transcript+reply written: {reply_wav} "
          f"({metrics['time_pipeline'] * 1e3:.1f} ms)")
    runtime.terminate()
    return 0


if __name__ == "__main__":
    sys.exit(main())
