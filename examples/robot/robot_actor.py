#!/usr/bin/env python3
"""VirtualRobot: an Actor with the XGO robot-dog command surface
(reference: examples/xgo_robot/xgo_robot.py:110-221 XGORobot -- action /
arm / attitude / claw / move / reset / stop / turn over the message
fabric).  Instead of driving hardware it integrates a simple kinematic
state into its ``share`` dict, so the Dashboard (or any ECConsumer)
watches the robot move and tests assert on poses without a robot-dog on
the desk.

Run standalone::

    python examples/robot/robot_actor.py        # + aiko_dashboard
"""

from __future__ import annotations

import math
import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from aiko_services_tpu.services import Actor

PROTOCOL_ROBOT = "robot:0"

ACTIONS = ("crawl", "pee", "sit", "sniff", "stretch", "wiggle_tail")


class VirtualRobot(Actor):
    """Kinematic twin of the reference's XGO robot-dog actor."""

    def __init__(self, name="virtual_robot", runtime=None):
        super().__init__(name, PROTOCOL_ROBOT, runtime=runtime)
        for key, value in (("x", 0.0), ("y", 0.0), ("heading", 0.0),
                           ("claw", 0), ("arm_x", 0), ("arm_z", 0),
                           ("pitch", 0), ("roll", 0), ("yaw", 0),
                           ("last_action", "none"), ("moving", False)):
            self.share[key] = value

    # -- the XGO command surface (each callable remotely by proxy) ----------

    def action(self, value):
        if value not in ACTIONS:
            self.logger.warning("unknown action %r", value)
            return
        self.ec_producer.update("last_action", value)
        self.ec_producer.update("moving", False)

    def arm(self, x, z):
        self.ec_producer.update("arm_x", int(x))
        self.ec_producer.update("arm_z", int(z))

    def attitude(self, pitch=0, roll=0, yaw=0):
        self.ec_producer.update("pitch", int(pitch))
        self.ec_producer.update("roll", int(roll))
        self.ec_producer.update("yaw", int(yaw))

    def claw(self, grip):
        self.ec_producer.update("claw", int(grip))

    def move(self, direction, stride=10):
        """Integrate one stride in the body frame (x forward, y left)."""
        stride = float(stride)
        heading = math.radians(float(self.share["heading"]))
        if direction == "x":
            dx = stride * math.cos(heading)
            dy = stride * math.sin(heading)
        else:
            dx = -stride * math.sin(heading)
            dy = stride * math.cos(heading)
        self.ec_producer.update("x", round(float(self.share["x"]) + dx, 3))
        self.ec_producer.update("y", round(float(self.share["y"]) + dy, 3))
        self.ec_producer.update("moving", True)

    def reset(self):
        for key in ("x", "y", "heading"):
            self.ec_producer.update(key, 0.0)
        for key in ("claw", "arm_x", "arm_z", "pitch", "roll", "yaw"):
            self.ec_producer.update(key, 0)
        self.ec_producer.update("last_action", "none")
        self.ec_producer.update("moving", False)

    def stop(self):
        self.ec_producer.update("moving", False)

    def turn(self, speed):
        heading = (float(self.share["heading"]) + float(speed)) % 360.0
        self.ec_producer.update("heading", heading)


def main():
    from aiko_services_tpu.runtime import init_process
    from aiko_services_tpu.services import Registrar

    runtime = init_process(transport="loopback")
    runtime.initialize()
    Registrar(runtime=runtime, primary_search_timeout=0.1)
    VirtualRobot(runtime=runtime)
    runtime.run()


if __name__ == "__main__":
    main()
