"""OODA-loop pipeline elements: Observe -> Orient -> Decide -> Act
(reference: examples/robot/ooda/elements.py:36-197 PromptMediaFusion /
RobotAgents / RobotActions).

The agentic pattern: perception elements (Detector, ASR, text input)
drop ``detections``/``texts`` into the swag; ``SensorFusion`` keeps a
short-term detection memory per stream (orient), ``RobotAgents`` seeds
each frame with the current world view (observe), and ``RobotActions``
turns S-expression commands into remote method calls on a robot Actor
discovered by service name (act) -- the same discovery/proxy machinery
as every other service, so the robot can live in another process or on
the real dog.

Commands are table-driven (reference's if-chain, elements.py:103-160):
``(forwards)``, ``(backwards)``, ``(turn left)``, ``(arm raise)``,
``(hand open)``, ``(sit)``, ``(stop)``, ``(reset)``, ...
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from aiko_services_tpu.pipeline import PipelineElement, StreamEvent
from aiko_services_tpu.services import ServiceFilter, do_discovery
from aiko_services_tpu.utils import parse

__all__ = ["SensorFusion", "RobotAgents", "RobotActions"]

DETECTION_MEMORY = 8          # frames a detection stays "oriented"


class SensorFusion(PipelineElement):
    """Merge fresh detections with a decaying per-stream memory
    (reference PromptMediaFusion, elements.py:36-57: "remove old
    detections, add new detections")."""

    def start_stream(self, stream, stream_id):
        stream.variables["fusion_memory"] = {}     # label -> frames left
        return StreamEvent.OKAY, {}

    def process_frame(self, stream, detections=None, texts=None):
        memory: dict = stream.variables["fusion_memory"]
        for label in list(memory):
            memory[label] -= 1
            if memory[label] <= 0:
                del memory[label]
        for detection in detections or []:
            label = detection.get("class") if isinstance(detection, dict) \
                else str(detection)
            memory[label] = DETECTION_MEMORY
        return StreamEvent.OKAY, {"detections": sorted(memory),
                                  "texts": list(texts or [])}


class RobotAgents(PipelineElement):
    """Seed each frame with the current world view so downstream agents
    always have ``detections``/``texts`` keys (reference RobotAgents,
    elements.py:196-206 create_initial_value)."""

    def process_frame(self, stream, **inputs):
        return StreamEvent.OKAY, {
            "detections": inputs.get("detections") or [],
            "texts": inputs.get("texts") or []}


# command word -> (method, fixed args) or a {qualifier: (method, args)}
# table keyed by the second token (reference elements.py:103-160).
COMMAND_TABLE = {
    "forwards": ("move", ["x", 10]),
    "backwards": ("move", ["x", -10]),
    "turn": {"left": ("turn", [40]), "right": ("turn", [-40])},
    "arm": {"lower": ("arm", [130, -40]), "raise": ("arm", [80, 80])},
    "hand": {"open": ("claw", [0]), "close": ("claw", [255])},
    "pitch": {"down": ("attitude", [15, 0, 0]),
              "up": ("attitude", [0, 0, 0])},
    "crawl": ("action", ["crawl"]),
    "pee": ("action", ["pee"]),
    "sit": ("action", ["sit"]),
    "sniff": ("action", ["sniff"]),
    "stretch": ("action", ["stretch"]),
    "wag": ("action", ["wiggle_tail"]),
    "stop": ("stop", []),
    "reset": ("reset", []),
}

ALIASES = {"r": "(reset)", "s": "(stop)"}


class RobotActions(PipelineElement):
    """Discover the robot Actor named by the ``service_name`` parameter
    and execute each frame's ``texts`` as robot commands (reference
    RobotActions, elements.py:60-193).  Emits ``actions``:
    ``[(text, status)]`` with status ok / unknown / no-robot."""

    def start_stream(self, stream, stream_id):
        service_name, found = self.get_parameter("service_name")
        if not found:
            return StreamEvent.ERROR, {
                "diagnostic": "must provide 'service_name' parameter"}
        stream.variables["robot_proxy"] = None

        def on_add(record, proxy):
            self.logger.info("discovered robot %s", record.topic_path)
            stream.variables["robot_proxy"] = proxy

        def on_remove(record, proxy):
            self.logger.warning("lost robot %s", record.topic_path)
            stream.variables["robot_proxy"] = None

        stream.variables["robot_discovery"] = do_discovery(
            self.pipeline.runtime,
            ServiceFilter(name=str(service_name)), on_add, on_remove)
        return StreamEvent.OKAY, {}

    def _execute(self, robot, text: str) -> str:
        command, parameters = parse(ALIASES.get(text, text))
        if command == "action" and parameters:    # "(action sit)" form
            command, parameters = str(parameters[0]), parameters[1:]
        entry = COMMAND_TABLE.get(command)
        if isinstance(entry, dict):
            qualifier = str(parameters[0]) if parameters else ""
            entry = entry.get(qualifier)
        if entry is None:
            return "unknown"
        method, args = entry
        getattr(robot, method)(*args)
        return "ok"

    def process_frame(self, stream, texts=None, **inputs):
        actions = []
        robot = stream.variables.get("robot_proxy")
        for text in texts or []:
            if not text:
                continue
            if robot is None:
                actions.append((text, "no-robot"))
                continue
            try:
                status = self._execute(robot, str(text))
            except Exception as error:
                self.logger.warning("command %r failed: %s", text, error)
                status = "error"
            actions.append((text, status))
            self.logger.info("%s: %s", status, text)
        return StreamEvent.OKAY, {"actions": actions}

    def stop_stream(self, stream, stream_id):
        discovery = stream.variables.pop("robot_discovery", None)
        if discovery is not None:
            discovery.terminate()
        stream.variables.pop("robot_proxy", None)
        return StreamEvent.OKAY, {}
