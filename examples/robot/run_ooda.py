#!/usr/bin/env python3
"""OODA demo: a VirtualRobot actor + the OODA pipeline in one process on
the loopback fabric.  Operator text commands flow observe -> orient ->
act and become remote method calls on the discovered robot; the robot's
kinematic state (watchable live in aiko_dashboard) prints at the end.

Run::

    python examples/robot/run_ooda.py
"""

import os
import queue
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from aiko_services_tpu.pipeline import create_pipeline
from aiko_services_tpu.runtime import init_process
from aiko_services_tpu.services import Registrar

from robot_actor import VirtualRobot


def main():
    runtime = init_process(transport="loopback")
    runtime.initialize()
    Registrar(runtime=runtime, primary_search_timeout=0.1)
    robot = VirtualRobot(runtime=runtime)

    pipeline = create_pipeline(
        os.path.join(os.path.dirname(__file__), "robot_pipeline.json"),
        runtime=runtime)
    responses = queue.Queue()
    stream = pipeline.create_stream_local("1", queue_response=responses)

    # Wait for the robot to be discovered, then issue the mission.
    runtime.run(until=lambda: stream.variables.get("robot_proxy")
                is not None, timeout=10.0)
    mission = [{"texts": ["(forwards)", "(forwards)"],
                "detections": [{"class": "oak_tree"}]},
               {"texts": ["(turn left)"], "detections": []},
               {"texts": ["(forwards)", "(sit)"], "detections": []}]
    for frame_data in mission:
        pipeline.create_frame_local(stream, frame_data)
    done = []
    # Proxy calls are asynchronous messages: wait for the robot's
    # mailbox to drain (the last command is the sit), not just for the
    # pipeline's frame responses.
    runtime.run(until=lambda: responses.qsize() >= len(mission)
                and robot.share["last_action"] == "sit", timeout=10.0)
    while not responses.empty():
        done.append(responses.get())
    for _, _, swag, _, okay, _ in done:
        print("actions:", swag.get("actions"),
              "| oriented:", swag.get("Fusion.detections"))
    print(f"robot pose: x={robot.share['x']} y={robot.share['y']} "
          f"heading={robot.share['heading']} "
          f"last_action={robot.share['last_action']}")


if __name__ == "__main__":
    main()
