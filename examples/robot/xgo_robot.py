#!/usr/bin/env python3
"""XGORobot: the real-hardware robot-dog actor (reference:
examples/xgo_robot/xgo_robot.py:110-221 XGORobot / XGORobotImpl, which
drives an XGO-Mini over serial via ``xgolib.XGO('/dev/ttyAMA0')``).

The serial layer is an injectable module hook (``xgo_factory``):
tests drive the actor with a mock backend asserting the exact command
traffic; on a robot the default factory opens the real xgolib port.
Every reference command (action/arm/arm_mode/attitude/body_mode/claw/
move/reset/stop/translation/turn) is exposed as an Actor method --
remotely callable by proxy over the fabric, exactly like the
reference's MQTT function calls from robot_control.py -- with the
reference's documented range clamps applied before they reach the
serial line.  A battery monitor timer mirrors
``BATTERY_MONITOR_PERIOD`` (xgo_robot.py:22) into the ``share`` dict
so the Dashboard shows charge state live.

Run on a robot::

    python examples/robot/xgo_robot.py          # + aiko_dashboard
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from aiko_services_tpu.services import Actor

PROTOCOL_XGO = "xgo_robot:0"

BATTERY_MONITOR_PERIOD = 10.0          # reference xgo_robot.py:22

# xgolib's numeric action ids (the serial protocol's contract; the
# reference carries the same table, xgo_robot.py:27-34).
ACTIONS = {
    "fall": 1, "stand": 2, "crawl": 3, "circle": 4, "step": 5,
    "squat": 6, "roll": 7, "pitch": 8, "yaw": 9, "roll_pitch_yaw": 10,
    "pee": 11, "sit": 12, "beckon": 13, "stretch": 14, "wave": 15,
    "wiggle_body": 16, "wiggle_tail": 17, "sniff": 18, "shake_paw": 19,
    "arm": 20,
}

# Reference range comments (xgo_robot.py:115-180), clamped here so a
# bad remote command can never reach the serial line out of range.
RANGES = {
    "arm_x": (-80, 155), "arm_z": (-95, 155),
    "pitch": (-15, 15), "roll": (-20, 10), "yaw": (-11, 11),
    "stride_x": (-25, 25), "stride_y": (-18, 18),
    "translation_x": (-35, 35), "translation_y": (-18, 18),
    "translation_z": (75, 115),
    "turn": (-100, 100), "claw": (0, 255),
}


def _clamp(name: str, value) -> int:
    low, high = RANGES[name]
    return int(min(max(float(value), low), high))


def _default_xgo_factory(port: str = "/dev/ttyAMA0",
                         version: str = "xgomini"):
    try:
        from xgolib import XGO                      # on-robot only
    except ImportError as error:
        raise RuntimeError(
            "xgolib not installed -- run on the robot, or inject a "
            "backend via examples.robot.xgo_robot.xgo_factory") \
            from error
    return XGO(port=port, version=version)


xgo_factory = _default_xgo_factory


class XGORobot(Actor):
    """Serial-attached XGO robot-dog (reference XGORobotImpl)."""

    def __init__(self, name="xgo_robot", runtime=None, backend=None,
                 port: str = "/dev/ttyAMA0"):
        super().__init__(name, PROTOCOL_XGO, tags=["ec=true"],
                         runtime=runtime)
        self._xgo = backend if backend is not None \
            else xgo_factory(port)
        self.share.update({
            "battery": -1,
            "version_firmware": str(getattr(
                self._xgo, "read_firmware", lambda: "v0")()),
            "last_action": "none",
        })
        self._battery_timer = self.runtime.engine.add_timer_handler(
            self._battery_monitor, BATTERY_MONITOR_PERIOD)

    # -- command surface (each remotely callable by proxy) -----------------

    def action(self, value):
        if value not in ACTIONS:
            self.logger.warning("unknown action %r", value)
            return
        self._xgo.action(ACTIONS[value])    # xgolib takes numeric ids
        self.ec_producer.update("last_action", value)

    def arm(self, x, z):
        self._xgo.arm(_clamp("arm_x", x), _clamp("arm_z", z))

    def arm_mode(self, stabilize):
        self._xgo.arm_mode(str(stabilize).lower() == "true")

    def attitude(self, pitch="nil", roll="nil", yaw="nil"):
        for axis, value in (("pitch", pitch), ("roll", roll),
                            ("yaw", yaw)):
            if value != "nil":
                # xgolib's attitude(direction, data) takes the
                # single-letter direction ('p'/'r'/'y').
                self._xgo.attitude(axis[0], _clamp(axis, value))

    def body_mode(self, stabilize):
        self._xgo.body_mode(str(stabilize).lower() == "true")

    def claw(self, grip):
        self._xgo.claw(_clamp("claw", grip))

    def move(self, direction, stride="nil"):
        if direction not in ("x", "y"):
            self.logger.warning("move direction %r not x|y", direction)
            return
        if stride != "nil":
            self._xgo.move(direction, _clamp(f"stride_{direction}",
                                             stride))

    def reset(self):
        self._xgo.reset()

    def stop(self):
        self._xgo.stop()

    def translation(self, x="nil", y="nil", z="nil"):
        for axis, value in (("x", x), ("y", y), ("z", z)):
            if value != "nil":
                self._xgo.translation(axis,
                                      _clamp(f"translation_{axis}",
                                             value))

    def turn(self, speed):
        self._xgo.turn(_clamp("turn", speed))

    def terminate(self, immediate=False):
        self.runtime.engine.remove_timer_handler(self._battery_timer)
        self._xgo.stop()
        self.runtime.engine.terminate()

    # -- telemetry ---------------------------------------------------------

    def _battery_monitor(self):
        read = getattr(self._xgo, "read_battery", None)
        if read is not None:
            self.ec_producer.update("battery", int(read()))


def main():
    from aiko_services_tpu.runtime import init_process

    runtime = init_process()
    runtime.initialize()
    XGORobot(runtime=runtime)
    runtime.run()


if __name__ == "__main__":
    main()
