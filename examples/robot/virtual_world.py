#!/usr/bin/env python3
"""VirtualWorld: a 3-D world for the VirtualRobot, rendered by a jitted
JAX raymarcher (reference: examples/robot/virtual/world.py -- 662 LoC
of Panda3D scene graph, window management, lighting and camera
controls driving a host GUI engine).

TPU-first counterpart: the world IS a signed-distance field and the
camera IS a jitted sphere-tracing renderer -- one functional
``render()`` over a [H*W] ray batch, compiled once per resolution,
running on whatever device hosts the pipeline.  No GUI toolkit, no
scene-graph objects: the scene is pose arrays, so the robot actor's
``share`` dict (x, y, heading -- the same state the Dashboard watches)
is the single source of truth and the renderer just reads it.

Scene: checkerboard ground, the robot dog (rounded-box body, four leg
capsules, a head cube with a snout marker), a red ball, grey box
obstacles.  Cameras: ``chase`` (third person, behind the robot) and
``eye`` (robot's view -- feed it to the Detector and the OODA loop
closes inside the virtual world).

Run a spinning demo::

    python examples/robot/virtual_world.py      # prints frame stats
"""

from __future__ import annotations

import dataclasses
import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(
    os.path.dirname(__file__), "..", "..")))

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["WorldConfig", "WorldState", "VirtualWorld", "render"]

MARCH_STEPS = 64
MAX_DISTANCE = 40.0
HIT_EPSILON = 1e-3

# Material ids (sky is "no hit").
GROUND, BODY, LIMB, BALL, OBSTACLE = 0, 1, 2, 3, 4
ALBEDO = jnp.asarray([
    [0.0, 0.0, 0.0],        # GROUND (checker applied separately)
    [0.85, 0.65, 0.2],      # BODY   (tan dog)
    [0.35, 0.25, 0.1],      # LIMB
    [0.9, 0.15, 0.1],       # BALL   (red)
    [0.5, 0.5, 0.55],       # OBSTACLE
])


@dataclasses.dataclass(frozen=True)
class WorldConfig:
    width: int = 160
    height: int = 120
    fov_degrees: float = 70.0
    n_obstacles: int = 2


@dataclasses.dataclass
class WorldState:
    """Pose arrays -- everything the SDF needs (the robot share's
    x/y/heading map to the ground plane; y-up in world space)."""
    robot_xz: np.ndarray          # [2]
    robot_heading: float          # radians
    ball_xz: np.ndarray           # [2]
    obstacle_xz: np.ndarray       # [N, 2]

    @classmethod
    def initial(cls, config: WorldConfig) -> "WorldState":
        spots = np.asarray([[3.0, 2.0], [-2.5, 3.5], [2.0, -3.0],
                            [-3.0, -2.0]], dtype=np.float32)
        if config.n_obstacles > len(spots):
            raise ValueError(f"n_obstacles <= {len(spots)} "
                             f"(got {config.n_obstacles})")
        return cls(robot_xz=np.zeros(2, dtype=np.float32),
                   robot_heading=0.0,
                   ball_xz=np.asarray([2.5, 0.5], dtype=np.float32),
                   obstacle_xz=spots[:config.n_obstacles])

    def as_arrays(self) -> tuple:
        return (jnp.asarray(self.robot_xz, jnp.float32),
                jnp.float32(self.robot_heading),
                jnp.asarray(self.ball_xz, jnp.float32),
                jnp.asarray(self.obstacle_xz, jnp.float32))


# ---------------------------------------------------------------------------
# Signed-distance primitives (vectorized over the ray batch [R, 3]).

def _sd_box(p, half):
    q = jnp.abs(p) - half
    outside = jnp.linalg.norm(jnp.maximum(q, 0.0), axis=-1)
    inside = jnp.minimum(jnp.max(q, axis=-1), 0.0)
    return outside + inside


def _sd_sphere(p, radius):
    return jnp.linalg.norm(p, axis=-1) - radius


def _sd_capsule(p, a, b, radius):
    pa, ba = p - a, b - a
    h = jnp.clip((pa @ ba) / (ba @ ba), 0.0, 1.0)
    return jnp.linalg.norm(pa - h[..., None] * ba, axis=-1) - radius


def _rotate_y(p, angle):
    c, s = jnp.cos(angle), jnp.sin(angle)
    x = c * p[..., 0] + s * p[..., 2]
    z = -s * p[..., 0] + c * p[..., 2]
    return jnp.stack([x, p[..., 1], z], axis=-1)


def _scene_sdf(p, robot_xz, heading, ball_xz, obstacle_xz):
    """[R, 3] points -> (distance [R], material [R])."""
    # Ground plane y = 0.
    best = p[..., 1]
    material = jnp.full(p.shape[:-1], GROUND, jnp.int32)

    def closer(distance, mat):
        nonlocal best, material
        material = jnp.where(distance < best, mat, material)
        best = jnp.minimum(best, distance)

    # Robot local frame (translate to pose, un-rotate heading).
    local = _rotate_y(
        p - jnp.stack([robot_xz[0], jnp.float32(0.0), robot_xz[1]]),
        -heading)
    body = _sd_box(local - jnp.asarray([0.0, 0.55, 0.0]),
                   jnp.asarray([0.55, 0.22, 0.3])) - 0.05
    closer(body, BODY)
    head = _sd_box(local - jnp.asarray([0.75, 0.85, 0.0]),
                   jnp.asarray([0.18, 0.16, 0.18])) - 0.03
    closer(head, BODY)
    snout = _sd_sphere(local - jnp.asarray([0.95, 0.8, 0.0]), 0.07)
    closer(snout, LIMB)
    for lx in (0.4, -0.4):
        for lz in (0.22, -0.22):
            leg = _sd_capsule(local, jnp.asarray([lx, 0.5, lz]),
                              jnp.asarray([lx, 0.0, lz]), 0.06)
            closer(leg, LIMB)

    ball = _sd_sphere(
        p - jnp.stack([ball_xz[0], jnp.float32(0.35), ball_xz[1]]),
        0.35)
    closer(ball, BALL)

    for i in range(obstacle_xz.shape[0]):
        centre = jnp.stack([obstacle_xz[i, 0], jnp.float32(0.5),
                            obstacle_xz[i, 1]])
        closer(_sd_box(p - centre, jnp.asarray([0.5, 0.5, 0.5])),
               OBSTACLE)
    return best, material


# ---------------------------------------------------------------------------
# Renderer.

@partial(jax.jit, static_argnames=("width", "height", "fov_degrees"))
def render(robot_xz, heading, ball_xz, obstacle_xz,
           camera_position, camera_target, *,
           width: int, height: int, fov_degrees: float = 70.0):
    """Sphere-trace the scene -> [height, width, 3] float32 in [0, 1].

    One jitted program over a [H*W] ray batch: camera basis, march
    loop (``lax.fori_loop``), finite-difference normals, lambertian
    shading with a sky gradient -- all static shapes, no host work.
    """
    forward = camera_target - camera_position
    forward = forward / jnp.linalg.norm(forward)
    right = jnp.cross(forward, jnp.asarray([0.0, 1.0, 0.0]))
    right = right / jnp.maximum(jnp.linalg.norm(right), 1e-6)
    up = jnp.cross(right, forward)

    tan_half = jnp.tan(jnp.deg2rad(fov_degrees) / 2.0)
    xs = (jnp.arange(width) + 0.5) / width * 2.0 - 1.0
    ys = 1.0 - (jnp.arange(height) + 0.5) / height * 2.0
    grid_x, grid_y = jnp.meshgrid(xs * tan_half * (width / height),
                                  ys * tan_half)
    directions = (forward[None, None]
                  + grid_x[..., None] * right[None, None]
                  + grid_y[..., None] * up[None, None])
    directions = directions / jnp.linalg.norm(directions, axis=-1,
                                              keepdims=True)
    rays = directions.reshape(-1, 3)                      # [R, 3]
    origin = camera_position[None]

    def sdf(points):
        return _scene_sdf(points, robot_xz, heading, ball_xz,
                          obstacle_xz)

    def march_step(_, t):
        distance, _mat = sdf(origin + t[:, None] * rays)
        return t + jnp.clip(distance, 0.0, 2.0) \
            * (t < MAX_DISTANCE)                # frozen past the far cap
    t = jax.lax.fori_loop(0, MARCH_STEPS, march_step,
                          jnp.full((rays.shape[0],), 0.1, jnp.float32))

    points = origin + t[:, None] * rays
    distance, material = sdf(points)
    hit = distance < 10 * HIT_EPSILON

    # Finite-difference normals (6 taps).
    eps = 1e-3
    normals = []
    for axis in range(3):
        offset = jnp.zeros(3).at[axis].set(eps)
        d_plus, _ = sdf(points + offset)
        d_minus, _ = sdf(points - offset)
        normals.append(d_plus - d_minus)
    normal = jnp.stack(normals, axis=-1)
    normal = normal / jnp.maximum(
        jnp.linalg.norm(normal, axis=-1, keepdims=True), 1e-6)

    light = jnp.asarray([0.45, 0.8, 0.35])
    light = light / jnp.linalg.norm(light)
    diffuse = jnp.clip(normal @ light, 0.0, 1.0)

    albedo = ALBEDO[jnp.clip(material, 0, ALBEDO.shape[0] - 1)]
    checker = ((jnp.floor(points[:, 0]) + jnp.floor(points[:, 2]))
               % 2.0)[..., None]
    ground_albedo = jnp.where(checker > 0.5,
                              jnp.asarray([0.75, 0.75, 0.7]),
                              jnp.asarray([0.35, 0.4, 0.35]))
    albedo = jnp.where((material == GROUND)[..., None], ground_albedo,
                       albedo)
    shaded = albedo * (0.25 + 0.75 * diffuse[..., None])

    sky_blend = jnp.clip(rays[:, 1] * 0.5 + 0.5, 0.0, 1.0)[..., None]
    sky = (jnp.asarray([0.75, 0.85, 1.0]) * sky_blend
           + jnp.asarray([0.95, 0.95, 0.9]) * (1.0 - sky_blend))
    color = jnp.where(hit[..., None], shaded, sky)
    return jnp.clip(color, 0.0, 1.0).reshape(height, width, 3)


# ---------------------------------------------------------------------------
# The world object (binds renderer to a robot actor's share dict).

class VirtualWorld:
    """Owns a :class:`WorldState` and renders camera views of it.

    ``sync(share)`` pulls the robot pose from a VirtualRobot share dict
    (the actor stays the single source of truth, exactly as the
    reference world mirrors xgo_robot state); ``camera_image`` renders
    ``chase`` or ``eye`` views as float32 numpy images.
    """

    def __init__(self, config: WorldConfig | None = None):
        self.config = config or WorldConfig()
        self.state = WorldState.initial(self.config)

    def sync(self, share: dict):
        self.state.robot_xz = np.asarray(
            [float(share.get("x", 0.0)), float(share.get("y", 0.0))],
            dtype=np.float32)
        self.state.robot_heading = float(
            np.deg2rad(float(share.get("heading", 0.0))))

    def _cameras(self):
        x, z = self.state.robot_xz
        heading = self.state.robot_heading
        forward = np.asarray([np.cos(heading), 0.0, np.sin(heading)],
                             dtype=np.float32)
        centre = np.asarray([x, 0.6, z], dtype=np.float32)
        return {
            "chase": (centre - 4.5 * forward
                      + np.asarray([0.0, 2.2, 0.0], np.float32),
                      centre),
            "eye": (centre + 0.9 * forward
                    + np.asarray([0.0, 0.35, 0.0], np.float32),
                    centre + 5.0 * forward),
        }

    def camera_image(self, camera: str = "chase") -> np.ndarray:
        cameras = self._cameras()
        if camera not in cameras:
            raise ValueError(f"camera {camera!r}: one of "
                             f"{sorted(cameras)}")
        position, target = cameras[camera]
        image = render(*self.state.as_arrays(),
                       jnp.asarray(position), jnp.asarray(target),
                       width=self.config.width,
                       height=self.config.height,
                       fov_degrees=self.config.fov_degrees)
        return np.asarray(image)


# ---------------------------------------------------------------------------
# Pipeline source: rendered frames into the dataflow (world -> Detector
# -> OODA closes the loop without a physical camera or robot).

_BOUND: dict = {"world": None, "share": None}


def bind_world(world: VirtualWorld, share: dict | None = None):
    """Attach the world (and optionally a robot actor's live share
    dict) that :class:`VirtualWorldCamera` instances render."""
    _BOUND["world"] = world
    _BOUND["share"] = share


from aiko_services_tpu.pipeline import (PipelineElement,      # noqa: E402
                                        StreamEvent)


class VirtualWorldCamera(PipelineElement):
    """Source element: each frame syncs the bound world to the robot
    share and emits the rendered camera ``image``.  Parameters:
    ``camera`` (``chase`` | ``eye``), ``rate``, ``frames`` (stop after
    N; 0 = endless)."""

    def start_stream(self, stream, stream_id):
        if _BOUND["world"] is None:
            return StreamEvent.ERROR, {
                "diagnostic": "no world bound (call "
                              "virtual_world.bind_world first)"}
        rate, _ = self.get_parameter("rate", None)
        stream.variables["world_frames"] = 0
        self.create_frames(stream, self._generate,
                           rate=float(rate) if rate else None)
        return StreamEvent.OKAY, {}

    def _generate(self, stream):
        world = _BOUND["world"]
        limit, _ = self.get_parameter("frames", 0)
        count = stream.variables["world_frames"]
        if limit and count >= int(limit):
            return StreamEvent.STOP, {}
        stream.variables["world_frames"] = count + 1
        if _BOUND["share"] is not None:
            world.sync(_BOUND["share"])
        camera, _ = self.get_parameter("camera", "chase")
        return StreamEvent.OKAY, {
            "image": world.camera_image(str(camera))}

    def process_frame(self, stream, **inputs):
        return StreamEvent.OKAY, inputs


def main():
    world = VirtualWorld(WorldConfig(width=96, height=72))
    for step in range(8):
        world.state.robot_heading = step * np.pi / 4
        image = world.camera_image("chase")
        print(f"frame {step}: shape={image.shape} "
              f"mean={image.mean():.3f}")


if __name__ == "__main__":
    main()
