"""DashboardModel (UI-free dashboard core) and CLI commands, offline."""

import json

from click.testing import CliRunner
from conftest import run_until

from aiko_services_tpu.dashboard import DashboardModel
from aiko_services_tpu.services import Actor, Registrar


class Worker(Actor):
    def __init__(self, name, runtime=None):
        super().__init__(name, "test/worker:0", runtime=runtime)
        self.share["temperature"] = 20

    def warm_up(self):
        self.ec_producer.update("temperature", 99)


def test_dashboard_model_directory_and_share(runtime):
    Registrar(runtime=runtime, primary_search_timeout=0.05)
    worker = Worker("worker_a", runtime=runtime)
    model = DashboardModel(runtime)

    assert run_until(
        runtime,
        lambda: any(r.name == "worker_a" for r in model.services()),
        timeout=5.0)

    model.select(worker.topic_path)
    assert run_until(runtime,
                     lambda: model.share_view.get("temperature") == "20",
                     timeout=5.0)
    items = dict(model.share_items())
    assert items["lifecycle"] == "ready"

    # Live share mutation propagates to the dashboard view.
    worker.warm_up()
    assert run_until(runtime,
                     lambda: model.share_view.get("temperature") == "99",
                     timeout=5.0)

    # Remote update through the dashboard changes the worker itself.
    model.update_share("log_level", "DEBUG")
    assert run_until(runtime,
                     lambda: worker.share["log_level"] == "DEBUG",
                     timeout=5.0)

    # Log tail.
    worker.logger.info("dashboard sees this")
    assert run_until(
        runtime,
        lambda: any("dashboard sees this" in line
                    for line in model.log_lines),
        timeout=5.0)

    model.terminate()
    assert model.selected is None and not model.share_view


def _definition(tmp_path):
    definition = {
        "version": 0, "name": "cli_pipe", "runtime": "jax",
        "graph": ["(echo)"],
        "elements": [{
            "name": "echo",
            "input": [{"name": "text"}],
            "output": [{"name": "text"}],
            "deploy": {"local": {
                "module": "aiko_services_tpu.elements.common",
                "class_name": "Identity"}}}]}
    path = tmp_path / "pipe.json"
    path.write_text(json.dumps(definition))
    return str(path)


def test_cli_pipeline_validate(tmp_path):
    from aiko_services_tpu.cli import main

    result = CliRunner().invoke(
        main, ["pipeline", "validate", _definition(tmp_path)])
    assert result.exit_code == 0, result.output
    data = json.loads(result.output)
    assert data["name"] == "cli_pipe"
    assert data["elements"] == ["echo"]


def test_cli_pipeline_validate_rejects_bad(tmp_path):
    from aiko_services_tpu.cli import main

    path = tmp_path / "bad.json"
    path.write_text(json.dumps({"version": 0, "name": "x"}))
    result = CliRunner().invoke(main, ["pipeline", "validate", str(path)])
    assert result.exit_code != 0


def test_dashboard_plugin_registry_and_registrar_view(runtime):
    """Per-protocol plugins (reference dashboard_plugins.py:1-52):
    protocol match, name-match precedence, and the built-in Registrar
    view rendering directory statistics."""
    from aiko_services_tpu.dashboard import (
        RegistrarPlugin, ServicePlugin, plugin_for, register_plugin,
        _PLUGINS)
    from aiko_services_tpu.pipeline import PROTOCOL_PIPELINE

    # The statically registered pipeline key matches the real constant.
    assert PROTOCOL_PIPELINE in _PLUGINS

    registrar = Registrar(runtime=runtime, primary_search_timeout=0.05)
    Worker("worker_b", runtime=runtime)
    model = DashboardModel(runtime)
    assert run_until(
        runtime, lambda: len(model.services()) >= 2, timeout=5.0)

    registrar_record = next(r for r in model.services()
                            if r.topic_path == registrar.topic_path)
    assert isinstance(plugin_for(registrar_record), RegistrarPlugin)
    worker_record = next(r for r in model.services()
                         if r.name == "worker_b")
    assert plugin_for(worker_record) is None      # no plugin registered

    model.select(registrar.topic_path)
    assert run_until(
        runtime,
        lambda: model.share_view.get("service_count") is not None,
        timeout=5.0)
    title, lines = model.plugin_view()
    assert title == "registrar"
    assert any("service_count" in line for line in lines)
    assert any("registrar" in line for line in lines)   # by-protocol table

    # Name-keyed plugin overrides a protocol-keyed one.
    class NamePlugin(ServicePlugin):
        title = "named"

        def render(self, model, record):
            return ["custom"]

    register_plugin("worker_b", NamePlugin)
    try:
        assert isinstance(plugin_for(worker_record), NamePlugin)
        model.select(worker_record.topic_path)
        assert model.plugin_view() == ("named", ["custom"])
    finally:
        _PLUGINS.pop("worker_b", None)
    model.terminate()


def test_dashboard_kill_and_copy_actions(runtime):
    """Service-kill and copy-topic dashboard actions (reference
    dashboard.py:399-408 _kill_service, :519-520 clipboard copy),
    model-level with injected kill/copier."""
    Registrar(runtime=runtime, primary_search_timeout=0.05)
    worker = Worker("worker_k", runtime=runtime)
    model = DashboardModel(runtime)
    assert run_until(
        runtime,
        lambda: any(r.name == "worker_k" for r in model.services()),
        timeout=5.0)

    killed, copied = [], []
    # Nothing selected: both actions are no-ops.
    assert model.kill_selected(kill=lambda *a: killed.append(a)) is False
    assert model.copy_selected_topic(copier=copied.append) is None

    model.select(worker.topic_path)
    assert model.copy_selected_topic(copier=copied.append) \
        == (worker.topic_path, True)
    assert copied == [worker.topic_path]

    # The worker lives in THIS process: killing it would kill the
    # dashboard itself, which the guard refuses.
    assert model.kill_selected(kill=lambda *a: killed.append(a)) is False
    assert killed == []

    # A same-host service in another process parses and kills.
    import signal
    parts = worker.topic_path.split("/")
    other = "/".join(parts[:-2] + [str(int(parts[-2]) + 1), "1"])
    model.selected = other
    assert model.kill_selected(kill=lambda *a: killed.append(a)) is True
    assert killed == [(int(parts[-2]) + 1, signal.SIGKILL)]

    # A service on another host refuses (the reference's documented
    # same-system limitation, made explicit).
    model.selected = f"{parts[0]}/elsewhere/12345/1"
    assert model.kill_selected(kill=lambda *a: killed.append(a)) is False
    assert len(killed) == 1


def test_dashboard_pipeline_plugin_renders_telemetry():
    """The pipeline plugin renders the telemetry.* rollup the pipeline
    publishes on its share dict (values arrive as strings through the
    ECConsumer; the renderer must not require numbers)."""
    from aiko_services_tpu.dashboard import PipelinePlugin

    class FakeModel:
        share_view = {
            "element_count": 2, "streams": 1, "frames_processed": 6,
            "telemetry": {
                "frame": {"count": "6", "p50_ms": "2.1",
                          "p90_ms": "3.0", "p99_ms": "3.2"},
                "element": {"A": {"count": "6", "p50_ms": "0.4",
                                  "p99_ms": "0.9"}},
                "stage": {}, "segment": {}, "hop": {}, "queue": {},
                "traces": {"buffered": "6", "completed": "6"}}}

        def share_items(self):
            return []

    lines = PipelinePlugin().render(FakeModel(), record=None)
    joined = "\n".join(lines)
    assert "[telemetry]" in joined
    assert "frame latency ms p50/p90/p99: 2.1/3.0/3.2 n=6" in joined
    assert any("A" in line and "0.4/0.9" in line for line in lines)
    assert "traces: 6 buffered / 6 completed" in joined

    # No telemetry published (telemetry: off): section omitted cleanly.
    class BareModel(FakeModel):
        share_view = {"element_count": 2, "streams": 0,
                      "frames_processed": 0}

    assert "[telemetry]" not in "\n".join(
        PipelinePlugin().render(BareModel(), record=None))
