"""Static hook-registry consistency (ISSUE 4 satellite): every hook
name registered via ``add_hook`` must have a matching ``run_hook``
call site and vice versa, and every consumer-side reference
(``add_hook_handler`` literals, CLI aliases) must point at a hook some
component actually runs -- so span/metric hooks cannot silently drift
when one side is renamed."""

import pathlib
import re

PACKAGE = pathlib.Path(__file__).resolve().parent.parent \
    / "aiko_services_tpu"

# "component.hook_name:version" -- the naming convention every hook in
# the tree follows (runtime/hooks.py).
_HOOK_NAME = r"[a-z_][a-z0-9_.]*:\d+"
_LITERAL = rf'"({_HOOK_NAME})"'
# HOOK_MESSAGE_IN = "actor.message_in:0" style constants, so
# add_hook(self.HOOK_X) / run_hook(self.HOOK_X) resolve too.
_CONSTANT = re.compile(rf'\b(HOOK_[A-Z_0-9]+)\s*=\s*{_LITERAL}')


def _sources():
    for path in sorted(PACKAGE.rglob("*.py")):
        yield path, path.read_text()


def _collect(call: str) -> dict[str, set]:
    """hook name -> set of 'file:line' sites for ``call(...)``."""
    constants: dict[str, str] = {}
    for _, text in _sources():
        for name, value in _CONSTANT.findall(text):
            constants[name] = value
    sites: dict[str, set] = {}
    pattern = re.compile(
        rf'\b{call}\(\s*(?:{_LITERAL}|(?:self|cls)\.(HOOK_[A-Z_0-9]+))')
    for path, text in _sources():
        for line_number, line in enumerate(text.splitlines(), 1):
            for literal, constant in pattern.findall(line):
                name = literal or constants.get(constant)
                if name is None:
                    raise AssertionError(
                        f"{path}:{line_number}: {call} uses unresolved "
                        f"constant {constant!r}")
                sites.setdefault(name, set()).add(
                    f"{path.relative_to(PACKAGE)}:{line_number}")
    return sites


def test_every_registered_hook_is_invoked_and_vice_versa():
    registered = _collect("add_hook")
    invoked = _collect("run_hook")
    assert registered, "no add_hook sites found -- pattern drift?"
    orphans = {name: sorted(sites) for name, sites in registered.items()
               if name not in invoked}
    assert not orphans, \
        f"hooks registered but never run (dead hooks): {orphans}"
    ghosts = {name: sorted(sites) for name, sites in invoked.items()
              if name not in registered}
    assert not ghosts, \
        f"hooks run but never registered (silent no-ops): {ghosts}"


def test_handler_attachments_reference_live_hooks():
    """add_hook_handler auto-registers, so a typo'd name would attach
    a handler to a hook nothing ever runs -- catch it statically."""
    invoked = set(_collect("run_hook"))
    attachments = _collect("add_hook_handler")
    stale = {name: sorted(sites) for name, sites in attachments.items()
             if name not in invoked}
    assert not stale, f"handlers attached to never-run hooks: {stale}"


def test_cli_hook_aliases_reference_live_hooks():
    from aiko_services_tpu.cli import _HOOK_ALIASES

    invoked = set(_collect("run_hook"))
    stale = {alias: name for alias, name in _HOOK_ALIASES.items()
             if name not in invoked}
    assert not stale, f"CLI aliases for never-run hooks: {stale}"


def test_pipeline_telemetry_and_profiler_cover_same_hooks():
    """The telemetry plane and the xprof profiler must stay in sync on
    the span-bearing hooks: a hook one consumes and the other misses is
    exactly the drift this check exists to catch."""
    profiler_attach = set()
    telemetry_attach = set()
    for path, text in _sources():
        names = set(re.findall(rf'"(pipeline\.[a-z_]+:\d+)"', text))
        if path.name == "profiling.py":
            profiler_attach = names
        elif path.name == "telemetry.py":
            telemetry_attach = names
    span_hooks = {"pipeline.process_element:0",
                  "pipeline.process_element_post:0",
                  "pipeline.process_segment:0",
                  "pipeline.process_segment_post:0",
                  "pipeline.process_stage:0",
                  "pipeline.process_stage_post:0",
                  "pipeline.stage_hop:0"}
    assert span_hooks <= profiler_attach
    assert span_hooks <= telemetry_attach
