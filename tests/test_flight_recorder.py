"""Flight recorder + critical-path attribution (ISSUE 10): the event
ring's cost contract (no-op when off, <= 1% fps when on), bucket
attribution summing to measured e2e within 5%, explain()/explain_frame
surfaces (API + HTTP), and the black-box dump a device_kill leaves
behind -- with the offline CLI rendering it."""

import json
import queue
import time
import urllib.request

import numpy as np
import pytest

from conftest import run_until

from aiko_services_tpu.observability import (
    BUCKETS, FlightRecorder, MetricsServer, attribute_events,
    attribute_metrics, events_as_dicts, render_buckets,
    render_timeline, write_blackbox)
from aiko_services_tpu.pipeline import (Pipeline, PipelineElement,
                                        StreamEvent)

COMMON = "aiko_services_tpu.elements.common"


class Sleeper(PipelineElement):
    """Deterministic host-side work: fps is sleep-bound, so the
    recorder's per-event cost is measurable against it."""

    def process_frame(self, stream, x):
        sleep_ms, _ = self.get_parameter("sleep_ms", 4.0)
        time.sleep(float(sleep_ms) / 1000.0)
        return StreamEvent.OKAY, {"x": x}


def element(name, cls="StageWork", module=COMMON, parameters=None,
            placement=None):
    entry = {"name": name, "input": [{"name": "x"}],
             "output": [{"name": "x"}],
             "parameters": parameters or {},
             "deploy": {"local": {"module": module, "class_name": cls}}}
    if placement:
        entry["placement"] = placement
    return entry


def pump(runtime, pipeline, n, stream_id="s", value=None):
    responses = queue.Queue()
    for i in range(n):
        pipeline.process_frame_local(
            {"x": np.float32(i) if value is None else value},
            stream_id=stream_id, queue_response=responses)
    assert run_until(runtime, lambda: responses.qsize() >= n,
                     timeout=60.0)
    rows = [responses.get() for _ in range(n)]
    assert all(row[4] for row in rows), \
        [row[5] for row in rows if not row[4]]
    return rows


# -- recorder units ----------------------------------------------------------

def test_ring_bounds_and_snapshot_filters():
    recorder = FlightRecorder(capacity=64)
    for i in range(200):
        recorder.record("dispatch", "s", i % 4, "el")
    assert len(recorder) == 64                  # bounded
    assert recorder.recorded == 200
    only = recorder.snapshot(stream="s", frame=1)
    assert only and all(event[3] == 1 for event in only)
    assert recorder.snapshot(tail=5) == recorder.snapshot()[-5:]
    # global events (stream/frame None) never join a frame's timeline
    recorder.record("llm_block", None, None, "dispatch")
    assert all(event[1] != "llm_block"
               for event in recorder.snapshot(frame=1))


def test_record_cost_is_microseconds():
    """The always-on contract: one event is a tuple append -- if this
    regresses to dict/lock territory the e2e overhead gate follows."""
    recorder = FlightRecorder(capacity=4096)
    count = 20000
    start = time.perf_counter()
    for i in range(count):
        recorder.record("dispatch", "s", i, "el")
    per_event = (time.perf_counter() - start) / count
    assert per_event < 20e-6, f"{per_event * 1e6:.2f} us/event"


def test_events_as_dicts_and_blackbox_prune(tmp_path):
    recorder = FlightRecorder()
    recorder.record("ingest", "s", 0)
    recorder.record("hop", "s", 0, "det", 1.25, {"replica": 1})
    dicts = events_as_dicts(recorder.snapshot())
    assert dicts[1]["type"] == "hop" and dicts[1]["ms"] == 1.25
    assert dicts[1]["replica"] == 1
    for i in range(5):
        write_blackbox(tmp_path, {"reason": f"r{i}", "events": dicts},
                       limit=3)
    dumps = sorted(tmp_path.glob("blackbox_*.json"))
    assert len(dumps) == 3                      # oldest pruned
    payload = json.loads(dumps[-1].read_text())
    assert payload["reason"] == "r4"


def test_blackbox_redacts_unserializable():
    import pathlib
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        path = write_blackbox(tmp, {"reason": "x",
                                    "bad": np.zeros((2, 2))})
        payload = json.loads(pathlib.Path(path).read_text())
        assert payload["bad"] == "<ndarray>"    # type name, no bytes


# -- attribution units -------------------------------------------------------

def test_attribute_events_state_machine():
    base = 100.0
    events = [
        (base + 0.000, "ingest", "s", 0, None, None, None),
        (base + 0.004, "pace", "s", 0, None, 3.0, None),     # 3ms pace
        (base + 0.005, "dispatch", "s", 0, "A", None, None),
        (base + 0.015, "dispatch_done", "s", 0, "A", 10.0, None),
        (base + 0.016, "hop", "s", 0, "B", 1.0, None),       # 1ms hop
        (base + 0.017, "park", "s", 0, "R", None, {"kind": "remote"}),
        (base + 0.027, "response", "s", 0, "R", None, None),
        (base + 0.030, "done", "s", 0, None, None, {"ok": True}),
    ]
    report = attribute_events(events)
    buckets = report["buckets"]
    assert buckets["pacing"] == pytest.approx(3.0, abs=0.01)
    assert buckets["compute"] == pytest.approx(10.0, abs=0.01)
    assert buckets["hop"] == pytest.approx(1.0, abs=0.01)
    assert buckets["pipe"] == pytest.approx(10.0, abs=0.01)
    # totality: every interval lands in a bucket, sums == event span
    assert sum(buckets.values()) == pytest.approx(report["e2e_ms"],
                                                  abs=0.01)
    assert report["e2e_ms"] == pytest.approx(30.0, abs=0.01)
    assert len(report["timeline"]) == len(events)
    assert render_timeline(report["timeline"])  # renders without error


def test_attribute_events_replay_reclassifies():
    events = [
        (0.000, "ingest", "s", 0, None, None, None),
        (0.001, "dispatch", "s", 0, "A", None, None),
        (0.021, "replay", "s", 0, "A", None, {"attempt": 1}),
        (0.025, "dispatch", "s", 0, "A", None, None),
        (0.035, "done", "s", 0, None, None, {"ok": True}),
    ]
    report = attribute_events(events)
    # the 20ms of in-flight work the replay voided bills to replay,
    # the re-run's 10ms to compute
    assert report["buckets"]["replay"] == pytest.approx(20.0, abs=0.01)
    assert report["buckets"]["compute"] == pytest.approx(10.0, abs=0.01)


def test_attribute_metrics_classification():
    metrics = {"time_pipeline": 0.100,
               "A_time": 0.040, "A_time_start": 123.0,
               "stage_B_wait_ms": 10.0, "B_queue_ms": 5.0,
               "B_hop_ms": 2.0, "B_time": 0.020,
               "stage_B_replica": 1,
               "A_fetch_ms": 3.0, "remote_C_ms": 15.0,
               "ingest_pace_ms": 4.0, "replay_lost_ms": 1.0,
               "stage_B_ms": 999.0,     # residency total: NOT a bucket
               "deadline_missed": True}
    report = attribute_metrics(metrics)
    buckets = report["buckets"]
    assert buckets["compute"] == pytest.approx(60.0)
    assert buckets["queue"] == pytest.approx(15.0)
    assert buckets["hop"] == pytest.approx(2.0)
    assert buckets["fetch"] == pytest.approx(3.0)
    assert buckets["pipe"] == pytest.approx(15.0)
    assert buckets["pacing"] == pytest.approx(4.0)
    assert buckets["replay"] == pytest.approx(1.0)
    assert set(buckets) == set(BUCKETS)
    # per-stage carries the replica suffix
    assert report["stages"]["B#1"]["compute"] == pytest.approx(20.0)
    assert "stage_B_ms" not in str(report)      # residency not double-counted
    assert render_buckets(report)


# -- acceptance: buckets sum to measured e2e within 5% -----------------------

def placed_pipeline(runtime, name="p_sum", parameters=None):
    return Pipeline(
        {"version": 0, "name": name, "runtime": "jax",
         "graph": ["(sa (sb))"],
         "parameters": dict(parameters or {}),
         "elements": [
             element("sa", parameters={"busy_ms": 20.0},
                     placement={"mesh": {"dp": 4}}),
             element("sb", parameters={"busy_ms": 20.0},
                     placement={"mesh": {"dp": 4}})]},
        runtime=runtime)


def test_bucket_totals_sum_to_e2e_within_5pct(runtime):
    """The ISSUE 10 acceptance bar: per-frame bucket totals cover the
    measured e2e latency within 5% on a stage-parallel placed pipeline
    (compute on workers, admission waits, hops, worker queues)."""
    pipeline = placed_pipeline(runtime)
    pump(runtime, pipeline, 2)          # jit + fusion-plan warmup
    rows = pump(runtime, pipeline, 6)
    for *_, metrics, _okay, _diag in rows:
        report = attribute_metrics(metrics)
        assert report["e2e_ms"] > 0
        gap = abs(report["e2e_ms"] - report["attributed_ms"])
        assert gap / report["e2e_ms"] <= 0.05, (gap, report)
        assert report["buckets"]["compute"] >= 35.0   # 2 x 20ms busy
    # the aggregate view agrees
    explanation = pipeline.explain(top_k=3)
    assert explanation["frames"] >= 6
    assert explanation["top"][0]["bucket"] in ("compute", "queue")
    assert sum(explanation["buckets"].values()) > 0
    pipeline.stop()


def test_explain_frame_timeline_live(runtime):
    pipeline = placed_pipeline(runtime, name="p_tl")
    pump(runtime, pipeline, 2)
    pump(runtime, pipeline, 3)
    story = pipeline.explain_frame(3, "s")      # a post-warmup frame
    assert story is not None
    types = [entry["type"] for entry in story["timeline"]]
    assert types[0] == "ingest" and types[-1] == "done"
    for expected in ("stage_wait", "admit", "hop", "dispatch",
                     "dispatch_done", "release"):
        assert expected in types, (expected, types)
    assert story["buckets"]["compute"] > 0
    assert story["trace_id"] and story["spans"]
    # totality of the event timeline
    assert sum(story["buckets"].values()) == pytest.approx(
        story["e2e_ms"], rel=0.01)
    assert pipeline.explain_frame(99999, "s") is None
    pipeline.stop()


# -- overhead gate -----------------------------------------------------------

def test_recorder_overhead_under_1pct(runtime):
    """Recorder-on vs recorder-off fps on a sleep-bound pipeline:
    the event ring must cost <= 1% (it records ~6 events around two
    4 ms sleeps -- microseconds against milliseconds)."""
    def build(name, mode):
        return Pipeline(
            {"version": 0, "name": name, "runtime": "jax",
             "graph": ["(e1 (e2))"],
             "parameters": {"recorder": mode},
             "elements": [
                 element("e1", "Sleeper",
                         module="tests/test_flight_recorder.py",
                         parameters={"sleep_ms": 4.0}),
                 element("e2", "Sleeper",
                         module="tests/test_flight_recorder.py",
                         parameters={"sleep_ms": 4.0})]},
            runtime=runtime)

    def best_elapsed(pipeline, passes=3, frames=25):
        best = None
        for _ in range(passes):
            start = time.perf_counter()
            pump(runtime, pipeline, frames)
            elapsed = time.perf_counter() - start
            best = elapsed if best is None else min(best, elapsed)
        return best

    off = build("p_off", "off")
    on = build("p_on", "on")
    assert off.recorder is None and on.recorder is not None
    pump(runtime, off, 2)
    pump(runtime, on, 2)                # warm both
    # Wall-clock A/B at the ~1% scale is scheduler-jitter territory:
    # re-measure up to 3 times and pass on any clean attempt -- a
    # GENUINE >1% recorder cost fails all three, a background-load
    # blip on one attempt does not fail tier-1.
    overhead = None
    for _attempt in range(3):
        off_elapsed = best_elapsed(off)
        on_elapsed = best_elapsed(on)
        overhead = (on_elapsed - off_elapsed) / off_elapsed
        if overhead <= 0.01:
            break
    assert on.recorder.recorded > 0
    off.stop()
    on.stop()
    assert overhead <= 0.01, f"recorder overhead {overhead:.2%}"


def test_recorder_off_is_noop(runtime):
    pipeline = Pipeline(
        {"version": 0, "name": "p_noop", "runtime": "jax",
         "graph": ["(A)"],
         "parameters": {"recorder": "off"},
         "elements": [element("A", "Increment")]},
        runtime=runtime)
    rows = pump(runtime, pipeline, 3, value=1)
    assert rows[0][4]
    assert pipeline.recorder is None
    # no ring -> no recorder gauges, no event timeline; metric-based
    # attribution (telemetry) still works
    assert "aiko_recorder_events" not in pipeline.metrics_text()
    story = pipeline.explain_frame(0, "s")
    assert story is not None and "timeline" not in story
    assert story["buckets"]["compute"] >= 0
    pipeline.stop()


# -- black box: device_kill leaves a dump the CLI renders --------------------

def test_device_kill_blackbox_dump_and_cli(runtime, tmp_path):
    """Acceptance: an injected device_kill (FaultPlan) produces a
    black-box dump whose timeline contains the faulted frame's replay
    transition -- and the offline CLI renders it."""
    pipeline = Pipeline(
        {"version": 0, "name": "p_bb", "runtime": "jax",
         "graph": ["(sq)"],
         "parameters": {
             "blackbox_dir": str(tmp_path),
             "health_probe_timeout": 2.0,
             "fault_plan": {"rules": [
                 {"point": "element_raise", "target": "sq", "count": 1},
                 {"point": "device_kill", "target": "sq", "count": 1},
             ]}},
         "elements": [element("sq", "BusyStage",
                              module="tests/test_chaos.py",
                              parameters={"busy_ms": 0.0},
                              placement={"mesh": {"dp": 4}})]},
        runtime=runtime)
    rows = pump(runtime, pipeline, 1, stream_id="0",
                value=np.float32(3.0))
    assert rows[0][4], rows[0][5]
    assert pipeline.share["frames_replayed"] == 1
    assert pipeline.share["blackbox_dumps"] >= 1
    dumps = sorted(tmp_path.glob("blackbox_*.json"))
    assert dumps, "device_kill recovery wrote no black-box dump"
    payload = json.loads(dumps[0].read_text())
    assert payload["reason"] == "replay"
    assert payload["pipeline"] == "p_bb"
    replay_events = [event for event in payload["events"]
                     if event["type"] == "replay"]
    assert replay_events and replay_events[0]["frame"] == 0
    assert replay_events[0]["attempt"] == 1
    # redaction: frame states carry swag KEYS and numbers, no arrays
    for state in payload["frames"]:
        assert all(isinstance(v, (int, float, bool, str))
                   for v in state["metrics"].values())
    # the dispatch that died is on the timeline before the replay
    types = [event["type"] for event in payload["events"]
             if event.get("frame") == 0]
    assert "dispatch" in types[:types.index("replay")]
    pipeline.stop()

    from click.testing import CliRunner
    from aiko_services_tpu.cli import main as cli_main
    result = CliRunner().invoke(
        cli_main, ["explain", str(dumps[0]), "--frame", "0"])
    assert result.exit_code == 0, result.output
    assert "replay" in result.output
    assert "attribution:" in result.output
    assert "black box: replay" in result.output


def test_blackbox_debounced_per_reason(runtime, tmp_path):
    """A sustained failure episode (every frame missing its deadline)
    must cost ONE dump per cooldown window, not a serialize+glob on
    the event loop per failure."""
    pipeline = Pipeline(
        {"version": 0, "name": "p_db", "runtime": "jax",
         "graph": ["(A)"],
         "parameters": {"blackbox_dir": str(tmp_path)},
         "elements": [element("A", "Increment")]},
        runtime=runtime)
    for _ in range(5):
        pipeline._blackbox("deadline_miss", "s", 0)
    pipeline._blackbox("breaker_open", "s", 0)   # distinct reason
    assert pipeline.share["blackbox_dumps"] == 2
    assert len(list(tmp_path.glob("blackbox_*.json"))) == 2
    pipeline.stop()


def test_explain_frame_never_merges_same_id_streams(runtime):
    """Frame ids restart per stream: explain_frame(0) with no stream
    must pick ONE stream's frame 0 (the newest), never interleave two
    frames' events into a fictional timeline."""
    pipeline = Pipeline(
        {"version": 0, "name": "p_ids", "runtime": "jax",
         "graph": ["(A)"],
         "elements": [element("A", "Increment")]},
        runtime=runtime)
    pump(runtime, pipeline, 2, stream_id="a", value=1)
    pump(runtime, pipeline, 2, stream_id="b", value=1)
    story = pipeline.explain_frame(0)           # stream omitted
    assert story is not None
    raw = pipeline.recorder.snapshot(frame=0)
    assert {str(event[2]) for event in raw} == {"a", "b"}
    # ...but the story is single-stream (the newest: "b")
    assert story["stream"] == "b"
    assert len(story["timeline"]) == len(
        pipeline.recorder.snapshot(stream="b", frame=0))
    pipeline.stop()


def test_explain_frame_survives_stream_recreation(runtime):
    """A destroyed-and-recreated same-id stream restarts frame ids at
    0: explain_frame must use only the NEWEST incarnation's segment
    (split at the ring's stream_end marker), not merge both frame-0
    timelines or terminate at the dead incarnation's done event."""
    pipeline = Pipeline(
        {"version": 0, "name": "p_reinc", "runtime": "jax",
         "graph": ["(A)"],
         "elements": [element("A", "Increment")]},
        runtime=runtime)
    pump(runtime, pipeline, 1, stream_id="s", value=1)
    pipeline._destroy_stream_now("s")
    pump(runtime, pipeline, 1, stream_id="s", value=1)  # frame 0 again
    # the ring holds BOTH incarnations' frame-0 events...
    raw = pipeline.recorder.snapshot(stream="s", frame=0)
    assert sum(1 for event in raw if event[1] == "ingest") == 2
    # ...but the story is single-incarnation: one ingest, one done
    story = pipeline.explain_frame(0, "s")
    types = [entry["type"] for entry in story["timeline"]]
    assert types.count("ingest") == 1 and types.count("done") == 1
    assert types[-1] == "done"
    pipeline.stop()


def test_cli_interleaved_dump_skips_bogus_attribution(tmp_path):
    """A dump with no trigger frame (replica_failover) interleaves
    many frames: the CLI must render the raw timeline and point at
    --frame, NOT run the single-frame state machine across frames."""
    from click.testing import CliRunner
    from aiko_services_tpu.cli import main as cli_main

    dump = tmp_path / "blackbox_x_replica_failover.json"
    dump.write_text(json.dumps({
        "reason": "replica_failover", "pipeline": "p", "frame": None,
        "frames": [],
        "events": [
            {"t": 0.0, "type": "ingest", "stream": "s", "frame": 0},
            {"t": 0.01, "type": "ingest", "stream": "s", "frame": 1},
            {"t": 0.02, "type": "dispatch", "stream": "s", "frame": 0,
             "name": "A"},
            {"t": 0.03, "type": "done", "stream": "s", "frame": 0,
             "ok": True}]}))
    result = CliRunner().invoke(cli_main, ["explain", str(dump)])
    assert result.exit_code == 0, result.output
    assert "interleaved timeline" in result.output
    assert "\nattribution:" not in result.output   # no bucket table
    assert "re-run with --frame" in result.output
    assert "s/0" in result.output and "s/1" in result.output
    focused = CliRunner().invoke(
        cli_main, ["explain", str(dump), "--frame", "0"])
    assert focused.exit_code == 0, focused.output
    assert "attribution:" in focused.output


def test_cli_renders_saved_explain_frame_body(runtime, tmp_path):
    """A saved ``GET /explain?frame=`` body carries ``events`` as an
    integer COUNT -- the CLI must render its timeline, not mistake it
    for a black-box dump and iterate the int."""
    from click.testing import CliRunner
    from aiko_services_tpu.cli import main as cli_main

    pipeline = placed_pipeline(runtime, name="p_saved")
    pump(runtime, pipeline, 3)
    body = pipeline.explain_frame(1, "s")
    assert isinstance(body["events"], int)      # the collision shape
    saved = tmp_path / "explain_frame.json"
    saved.write_text(json.dumps(body))
    result = CliRunner().invoke(cli_main, ["explain", str(saved)])
    assert result.exit_code == 0, result.output
    assert "attribution:" in result.output
    assert "dispatch" in result.output
    pipeline.stop()


# -- HTTP surfaces -----------------------------------------------------------

def test_explain_http_route_and_traces_limit(runtime):
    pipeline = placed_pipeline(runtime, name="p_http10")
    pump(runtime, pipeline, 4)
    server = MetricsServer(pipeline, port=0, host="127.0.0.1")
    try:
        base = f"http://127.0.0.1:{server.port}"
        report = json.loads(urllib.request.urlopen(
            f"{base}/explain", timeout=5.0).read())
        assert report["frames"] >= 4 and report["top"]
        assert set(report["buckets"]) == set(BUCKETS)
        one = json.loads(urllib.request.urlopen(
            f"{base}/explain?frame=2&stream=s", timeout=5.0).read())
        assert one["frame"] == 2 and one["timeline"]
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            urllib.request.urlopen(f"{base}/explain?frame=424242",
                                   timeout=5.0)
        assert excinfo.value.code == 404
        # /traces?limit= (default 50) bounds the body
        payload = json.loads(urllib.request.urlopen(
            f"{base}/traces?limit=2", timeout=5.0).read())
        assert len(payload["traces"]) == 2
        payload = json.loads(urllib.request.urlopen(
            f"{base}/traces", timeout=5.0).read())
        assert len(payload["traces"]) <= 50
        for bad in ("0", "-3", "zzz"):
            with pytest.raises(urllib.error.HTTPError) as excinfo:
                urllib.request.urlopen(f"{base}/traces?limit={bad}",
                                       timeout=5.0)
            assert excinfo.value.code == 400
    finally:
        server.stop()
        pipeline.stop()
