"""Media element library: image/video/audio I/O + ZMQ/TTY schemes running
through real pipelines on the loopback runtime."""

import io
import queue

import jax.numpy as jnp
import numpy as np
import pytest

from conftest import run_until
from aiko_services_tpu.elements import read_wav, write_wav
from aiko_services_tpu.pipeline import Pipeline

MEDIA = "aiko_services_tpu.elements"


def element(name, cls, inputs, outputs, parameters=None):
    return {"name": name,
            "input": [{"name": n} for n in inputs],
            "output": [{"name": n} for n in outputs],
            "deploy": {"local": {"module": MEDIA, "class_name": cls}},
            "parameters": parameters or {}}


def definition(graph, elements, name="p_media"):
    return {"version": 0, "name": name, "runtime": "jax", "graph": graph,
            "parameters": {}, "elements": elements}


def pump_stream(runtime, pipeline, stream_id="s1", parameters=None,
                predicate=None, timeout=10.0):
    pipeline.create_stream_local(stream_id, parameters or {})
    if predicate is not None:
        assert run_until(runtime, predicate, timeout=timeout)


def make_image(tmp_path, name="in.png", size=(32, 24), color=(200, 30, 40)):
    from PIL import Image
    path = tmp_path / name
    Image.new("RGB", size, color).save(path)
    return path


# -- image ------------------------------------------------------------------

def test_image_read_resize_overlay_write(tmp_path, runtime):
    source = make_image(tmp_path)
    target = tmp_path / "out.png"
    pipeline = Pipeline(definition(
        ["(Read Resize Overlay Write)"],
        [element("Read", "ImageReadFile", ["path"], ["image"],
                 {"data_sources": f"file://{source}"}),
         element("Resize", "ImageResize", ["image"], ["image"],
                 {"width": 16, "height": 12}),
         element("Overlay", "ImageOverlay", ["image"], ["image"]),
         element("Write", "ImageWriteFile", ["image"], ["path"],
                 {"data_targets": f"file://{target}"})]),
        runtime=runtime)
    pump_stream(runtime, pipeline, predicate=lambda: target.exists())

    from PIL import Image
    with Image.open(target) as image:
        assert image.size == (16, 12)


def test_image_overlay_draws_rectangles(runtime):
    from aiko_services_tpu.elements.image import ImageOverlay
    from aiko_services_tpu.pipeline.element import ElementContext

    overlay = ImageOverlay(ElementContext(
        "o", None, _FakePipeline(), {}))
    image = jnp.zeros((20, 20, 3), dtype=jnp.uint8)
    event, outputs = overlay.process_frame(
        None, image=image,
        overlay={"rectangles": [
            {"x": 0.1, "y": 0.1, "w": 0.5, "h": 0.5, "name": "cat"}]})
    out = np.asarray(outputs["image"])
    assert out.sum() > 0                   # something was drawn


class _FakePipeline:
    def current_stream(self):
        return None

    def get_pipeline_parameter(self, name, default=None):
        return default


# -- video ------------------------------------------------------------------

def test_video_write_then_read(tmp_path, runtime):
    cv2 = pytest.importorskip("cv2")
    video_path = tmp_path / "clip.avi"
    frames = [np.full((24, 32, 3), i * 10, dtype=np.uint8)
              for i in range(5)]
    writer = cv2.VideoWriter(
        str(video_path), cv2.VideoWriter_fourcc(*"MJPG"), 10.0, (32, 24))
    assert writer.isOpened()
    for frame in frames:
        writer.write(frame)
    writer.release()

    collected = []

    import tests_media_helpers  # registered collector element
    tests_media_helpers.SINK = collected

    pipeline = Pipeline(definition(
        ["(Read Collect)"],
        [element("Read", "VideoReadFile", ["image"], ["image"],
                 {"data_sources": f"file://{video_path}"}),
         {"name": "Collect", "input": [{"name": "image"}],
          "output": [],
          "deploy": {"local": {"module": "tests_media_helpers",
                               "class_name": "Collect"}},
          "parameters": {}}]),
        runtime=runtime)
    pump_stream(runtime, pipeline,
                predicate=lambda: len(collected) >= 5)
    assert collected[0].shape == (24, 32, 3)


def test_video_sample_drops(runtime):
    from aiko_services_tpu.elements.video import VideoSample
    from aiko_services_tpu.pipeline.element import ElementContext
    from aiko_services_tpu.pipeline.stream import Stream
    from aiko_services_tpu.pipeline import StreamEvent

    sampler = VideoSample(ElementContext(
        "s", None, _FakePipeline(), {"sample_rate": 3}))
    stream = Stream(stream_id="x")
    sampler.start_stream(stream, "x")
    events = [sampler.process_frame(stream, image=i)[0] for i in range(6)]
    assert events == [StreamEvent.OKAY, StreamEvent.DROP_FRAME,
                      StreamEvent.DROP_FRAME, StreamEvent.OKAY,
                      StreamEvent.DROP_FRAME, StreamEvent.DROP_FRAME]


# -- audio ------------------------------------------------------------------

def test_wav_roundtrip(tmp_path):
    rate = 8000
    t = np.linspace(0, 1, rate, endpoint=False)
    tone = (0.5 * np.sin(2 * np.pi * 440 * t)).astype(np.float32)
    path = tmp_path / "tone.wav"
    write_wav(path, tone, rate)
    samples, read_rate = read_wav(str(path))
    assert read_rate == rate
    assert samples.shape == (rate, 1)
    np.testing.assert_allclose(samples[:, 0], tone, atol=1e-3)


def test_audio_pipeline_frame_fft(tmp_path, runtime):
    rate = 8000
    t = np.linspace(0, 0.1, rate // 10, endpoint=False)
    tone = (0.5 * np.sin(2 * np.pi * 1000 * t)).astype(np.float32)
    path = tmp_path / "in.wav"
    write_wav(path, tone, rate)

    import tests_media_helpers
    collected = []
    tests_media_helpers.SINK = collected

    pipeline = Pipeline(definition(
        ["(Read Frame FFT Collect)"],
        [element("Read", "AudioReadFile", ["path"], ["audio", "sample_rate"],
                 {"data_sources": f"file://{path}"}),
         element("Frame", "AudioFraming", ["audio"], ["frames"],
                 {"window": 256, "hop": 128}),
         element("FFT", "AudioFFT", ["frames"], ["spectrum"]),
         {"name": "Collect", "input": [{"name": "spectrum"}],
          "output": [],
          "deploy": {"local": {"module": "tests_media_helpers",
                               "class_name": "CollectSpectrum"}},
          "parameters": {}}]),
        runtime=runtime)
    pump_stream(runtime, pipeline, predicate=lambda: len(collected) >= 1)
    spectrum = np.asarray(collected[0])
    # peak bin should be at 1 kHz: bin = 1000 / (8000/256) = 32
    assert abs(int(spectrum[0].argmax()) - 32) <= 1


def test_audio_resampler():
    from aiko_services_tpu.elements.audio import AudioResampler
    from aiko_services_tpu.pipeline.element import ElementContext

    resampler = AudioResampler(ElementContext(
        "r", None, _FakePipeline(), {"target_rate": 4000}))
    audio = jnp.ones((8000,), dtype=jnp.float32)
    event, outputs = resampler.process_frame(None, audio=audio,
                                             sample_rate=8000)
    assert outputs["audio"].shape == (4000,)
    assert outputs["sample_rate"] == 4000


# -- zmq --------------------------------------------------------------------

def test_zmq_array_payload_roundtrip():
    from aiko_services_tpu.elements.scheme_zmq import (decode_payload,
                                                       encode_payload)
    x = jnp.arange(12.0).reshape(3, 4)
    decoded = decode_payload(encode_payload(x))
    np.testing.assert_array_equal(np.asarray(decoded), np.asarray(x))
    assert decode_payload(encode_payload("hello")) == "hello"
    assert decode_payload(encode_payload(b"raw")) == b"raw"


def test_zmq_pipeline_pair(tmp_path, runtime):
    """Writer pipeline PUSHes text, reader pipeline PULLs it."""
    zmq = pytest.importorskip("zmq")
    from aiko_services_tpu.utils import find_free_port
    port = find_free_port()

    import tests_media_helpers
    collected = []
    tests_media_helpers.SINK = collected

    reader = Pipeline(definition(
        ["(Read Collect)"],
        [element("Read", "TextReadZMQ", ["payload"], ["text"],
                 {"data_sources": f"zmq://127.0.0.1:{port}",
                  "zmq_bind": True}),
         {"name": "Collect", "input": [{"name": "text"}], "output": [],
          "deploy": {"local": {"module": "tests_media_helpers",
                               "class_name": "CollectText"}},
          "parameters": {}}], name="p_zmq_read"),
        runtime=runtime)
    reader.create_stream_local("rx", {})

    writer = Pipeline(definition(
        ["(Write)"],
        [element("Write", "TextWriteZMQ", ["text"], ["text"],
                 {"data_targets": f"zmq://127.0.0.1:{port}",
                  "zmq_bind": False})], name="p_zmq_write"),
        runtime=runtime)
    writer.create_stream_local("tx", {})
    run_until(runtime, lambda: False, timeout=0.2)   # let sockets settle

    responses = queue.Queue()
    writer.process_frame_local({"text": "over the wire"}, stream_id="tx",
                               queue_response=responses)
    assert run_until(runtime, lambda: len(collected) >= 1, timeout=10.0)
    assert collected[0] == "over the wire"


# -- tty --------------------------------------------------------------------

def test_tty_read_write(tmp_path, runtime):
    import tests_media_helpers
    collected = []
    tests_media_helpers.SINK = collected
    output = io.StringIO()

    pipeline = Pipeline(definition(
        ["(Read Write)"],
        [element("Read", "TextReadTTY", ["text"], ["text"],
                 {"data_sources": "tty://stdin"}),
         element("Write", "TextWriteTTY", ["text"], ["text"],
                 {"data_targets": "tty://stdout"})], name="p_tty"),
        runtime=runtime)
    # inject input/output streams via stream parameters
    pipeline.create_stream_local("t1", {
        "Read.tty_input": io.StringIO("alpha\nbeta\n/q\n"),
        "Write.tty_output": output})
    assert run_until(
        runtime, lambda: output.getvalue().count("\n") >= 2, timeout=10.0)
    assert output.getvalue() == "alpha\nbeta\n"


def test_audio_graph_xy():
    """AudioGraphXY renders the spectrum into an image array (reference
    PE_GraphXY parity, display-free): a tone's peak column draws a
    full-height bar, quiet columns stay near the baseline."""
    from aiko_services_tpu.elements.audio import AudioGraphXY
    from aiko_services_tpu.pipeline.element import ElementContext

    graph = AudioGraphXY(ElementContext(
        "g", None, _FakePipeline(), {"width": 128, "height": 64}))
    bins = 256
    spectrum = np.full((2, bins), 0.01, dtype=np.float32)
    spectrum[:, 64] = 1.0                       # peak at bin 64 -> col 32
    event, outputs = graph.process_frame(None, spectrum=spectrum,
                                         sample_rate=8000)
    image = outputs["image"]
    assert image.shape == (64, 128, 3) and image.dtype == np.uint8
    assert outputs["spectrum"] is spectrum      # passthrough
    bar_color = np.array([64, 200, 120], dtype=np.uint8)
    bar_rows = (image == bar_color).all(axis=-1).sum(axis=0)  # per column
    peak_col = int(bar_rows.argmax())
    assert abs(peak_col - 32) <= 1              # peak lands where it should
    assert bar_rows[peak_col] >= 60             # ~full height
    assert np.median(bar_rows) <= 3             # quiet floor stays low


def test_audio_graph_xy_max_frequency():
    from aiko_services_tpu.elements.audio import AudioGraphXY
    from aiko_services_tpu.pipeline.element import ElementContext

    graph = AudioGraphXY(ElementContext(
        "g", None, _FakePipeline(),
        {"width": 64, "height": 32, "max_frequency": 2000}))
    bins = 256                                  # nyquist 4 kHz at 8 kHz
    spectrum = np.full((bins,), 0.01, dtype=np.float32)
    spectrum[32] = 1.0                          # 0.5 kHz
    event, outputs = graph.process_frame(None, spectrum=spectrum,
                                         sample_rate=8000)
    image = outputs["image"]
    bar_color = np.array([64, 200, 120], dtype=np.uint8)
    bar_rows = (image == bar_color).all(axis=-1).sum(axis=0)
    # x axis now spans 0..2 kHz over 128 kept bins: the 0.5 kHz peak
    # lands at ~1/4 of the width instead of 1/8.
    assert abs(int(bar_rows.argmax()) - 16) <= 1


# -- media conversion utilities ---------------------------------------------

def test_images_to_video_to_images_roundtrip(tmp_path, runtime):
    """The conversion utilities (reference images_to_video.py:1-33,
    video_to_images.py:1-42): a directory of images encodes into a
    video; that video decodes back into the same number of frames."""
    cv2 = pytest.importorskip("cv2")
    del cv2
    from PIL import Image

    from aiko_services_tpu.media_convert import (images_to_video,
                                                 video_to_images)

    for i in range(5):
        Image.new("RGB", (32, 24), (i * 40, 30, 40)).save(
            tmp_path / f"frame_{i}.png")
    video = tmp_path / "clip.avi"
    frames = images_to_video(f"{tmp_path}/frame_*.png", str(video),
                             rate=10.0, runtime=runtime)
    assert frames == 5
    assert video.exists() and video.stat().st_size > 0

    out_pattern = tmp_path / "decoded" / "img_{}.png"
    frames = video_to_images(str(video), str(out_pattern),
                             runtime=runtime)
    assert frames == 5
    decoded = sorted((tmp_path / "decoded").glob("img_*.png"))
    assert len(decoded) == 5
    with Image.open(decoded[0]) as image:
        assert image.size == (32, 24)
