"""Telemetry plane (ISSUE 4): streaming log histograms, the metrics
registry + Prometheus exposition, the TraceBuffer ring, share rollups,
and the HTTP export surface -- plus the loop-confinement contract for
``frame.metrics`` under concurrent readers."""

import json
import queue
import threading
import time
import urllib.request

import pytest

from conftest import run_until

from aiko_services_tpu.observability import (LogHistogram,
                                             MetricsRegistry,
                                             MetricsServer, TraceBuffer,
                                             decode_spans, encode_spans,
                                             make_span, mint_id)
from aiko_services_tpu.pipeline import Pipeline

COMMON = "aiko_services_tpu.elements.common"


def element(name, cls, parameters=None, module=COMMON):
    return {"name": name, "input": [{"name": "x"}],
            "output": [{"name": "x"}],
            "deploy": {"local": {"module": module, "class_name": cls}},
            "parameters": parameters or {}}


def simple_pipeline(runtime, name="p_obs", parameters=None):
    return Pipeline({"version": 0, "name": name, "runtime": "jax",
                     "graph": ["(A (B))"],
                     "parameters": dict(parameters or {}),
                     "elements": [element("A", "Increment"),
                                  element("B", "Increment")]},
                    runtime=runtime)


def pump(runtime, pipeline, n, stream_id="s"):
    responses = queue.Queue()
    for i in range(n):
        pipeline.process_frame_local({"x": i}, stream_id=stream_id,
                                     queue_response=responses)
    assert run_until(runtime, lambda: responses.qsize() >= n,
                     timeout=20.0)
    rows = [responses.get() for _ in range(n)]
    assert all(row[4] for row in rows), rows
    return rows


# -- LogHistogram -----------------------------------------------------------

def test_histogram_quantiles_bounded_error():
    histogram = LogHistogram()
    for value in range(1, 1001):          # 1..1000 ms uniform
        histogram.observe(float(value))
    assert histogram.count == 1000
    for q, expected in ((0.5, 500.0), (0.9, 900.0), (0.99, 990.0)):
        measured = histogram.quantile(q, windowed=False)
        # log-bucket growth 2**0.25 -> relative error under ~10%
        assert abs(measured - expected) / expected < 0.12, (q, measured)
    summary = histogram.summary(windowed=False)
    assert summary["count"] == 1000
    assert summary["min_ms"] == 1.0 and summary["max_ms"] == 1000.0


def test_histogram_extremes_and_window_rotation(monkeypatch):
    histogram = LogHistogram(window_s=10.0)
    histogram.observe(0.0)                 # underflow bucket
    histogram.observe(1e9)                 # clamps to top bucket
    assert histogram.quantile(0.0, windowed=False) is not None
    # Force a rotation: old window values drop out of the windowed
    # view after two windows, but stay in the cumulative view.
    histogram.observe(5.0)
    histogram._window_start -= 25.0        # two windows ago
    histogram.observe(7.0)                 # triggers rotation
    assert histogram.quantile(0.5, windowed=False) is not None
    windowed = histogram.quantile(0.99, windowed=True)
    assert windowed is not None and windowed <= 8.0  # 1e9 rotated out


def test_empty_histogram():
    histogram = LogHistogram()
    assert histogram.quantile(0.5) is None
    assert histogram.summary()["p99_ms"] is None


# -- MetricsRegistry --------------------------------------------------------

def test_registry_labels_and_render_text():
    registry = MetricsRegistry()
    registry.observe("element_latency_ms", 3.0, element="DET")
    registry.observe("element_latency_ms", 30.0, element="LLM")
    registry.count("frames_total", status="ok")
    registry.count("frames_total", status="ok")
    registry.gauge("streams_active", 2)
    assert registry.quantile("element_latency_ms", 0.5,
                             {"element": "DET"}) == pytest.approx(
        3.0, rel=0.15)
    text = registry.render_text()
    assert "# TYPE aiko_element_latency_ms summary" in text
    assert 'aiko_element_latency_ms{element="DET",quantile="0.5"}' in text
    assert 'aiko_element_latency_ms_count{element="DET"} 1' in text
    assert 'aiko_frames_total{status="ok"} 2' in text
    assert "aiko_streams_active 2" in text
    registry.reset()
    assert registry.summaries() == []


def test_render_text_exposition_format():
    """Prometheus exposition contract (ISSUE 10 satellite): summaries
    carry `_sum`/`_count` per labeled series (so dashboards can
    compute rates/averages), one TYPE line per name, and NO duplicate
    samples -- a counter and a same-name gauge must not both emit (a
    duplicate sample invalidates the whole scrape)."""
    registry = MetricsRegistry()
    for value in (2.0, 4.0):
        registry.observe("latency_ms", value, element="A")
    registry.observe("latency_ms", 8.0, element="B")
    registry.count("frames_replayed", 3)
    registry.gauge("frames_replayed", 99)       # same-name refresh
    registry.gauge("depth", 7, stage="s")
    text = registry.render_text()
    lines = text.splitlines()
    # _sum/_count per labeled series, summing the observations
    assert 'aiko_latency_ms_sum{element="A"} 6' in text
    assert 'aiko_latency_ms_count{element="A"} 2' in text
    assert 'aiko_latency_ms_sum{element="B"} 8' in text
    assert 'aiko_latency_ms_count{element="B"} 1' in text
    # quantile samples carry the label plus quantile
    assert any(line.startswith('aiko_latency_ms{element="A"'
                               ',quantile="0.5"}') for line in lines)
    # one TYPE line per metric name
    type_lines = [line for line in lines if line.startswith("# TYPE")]
    assert len(type_lines) == len(set(type_lines))
    assert "# TYPE aiko_latency_ms summary" in type_lines
    # counter wins over the same-name gauge: exactly ONE sample
    samples = [line for line in lines
               if line.split("{")[0].split(" ")[0]
               == "aiko_frames_replayed"]
    assert samples == ["aiko_frames_replayed 3"]
    # no duplicate (name, labels) samples anywhere
    keys = [line.rsplit(" ", 1)[0] for line in lines
            if not line.startswith("#")]
    assert len(keys) == len(set(keys)), sorted(keys)


def test_pipeline_scrape_has_no_duplicate_samples(runtime):
    """Integration twin: after recovery counters fire (replay/shed
    share mirrors), a full metrics_text() scrape still has unique
    (name, labels) samples."""
    pipeline = simple_pipeline(runtime, name="p_dup")
    pump(runtime, pipeline, 3)
    # force the recovery counters that USED to be double-emitted
    pipeline.telemetry.registry.count("frames_replayed")
    pipeline.telemetry.registry.count("frames_shed")
    pipeline.telemetry.registry.count("deadline_misses")
    lines = [line for line in pipeline.metrics_text().splitlines()
             if line and not line.startswith("#")]
    keys = [line.rsplit(" ", 1)[0] for line in lines]
    duplicates = {key for key in keys if keys.count(key) > 1}
    assert not duplicates, duplicates
    pipeline.stop()


def test_registry_thread_safety_smoke():
    registry = MetricsRegistry()
    stop = threading.Event()
    errors = []

    def writer():
        i = 0
        while not stop.is_set():
            registry.observe("latency_ms", i % 50 + 0.1, element="A")
            registry.count("events")
            i += 1

    def reader():
        while not stop.is_set():
            try:
                registry.render_text()
                registry.quantile("latency_ms", 0.99, {"element": "A"})
            except Exception as error:      # pragma: no cover
                errors.append(error)
                return

    threads = [threading.Thread(target=fn)
               for fn in (writer, writer, reader, reader)]
    for thread in threads:
        thread.start()
    time.sleep(0.3)
    stop.set()
    for thread in threads:
        thread.join(timeout=5.0)
    assert not errors


# -- TraceBuffer / span codec -----------------------------------------------

def test_trace_buffer_ring_and_merge():
    buffer = TraceBuffer(capacity=3)
    ids = [mint_id() for _ in range(4)]
    for trace_id in ids:
        buffer.add(trace_id, [make_span(trace_id, mint_id(), None,
                                        "frame:0", "frame", "p", "s", 0,
                                        time.time(), 1.0)])
    assert len(buffer) == 3                      # oldest evicted
    assert buffer.get(ids[0]) is None
    # merge: same trace extended, okay AND-ed
    buffer.add(ids[-1], [make_span(ids[-1], mint_id(), None, "element:A",
                                   "element", "q", "s", 0, time.time(),
                                   2.0)], okay=False)
    merged = buffer.get(ids[-1])
    assert len(merged["spans"]) == 2 and merged["okay"] is False
    assert [t["trace_id"] for t in buffer.recent(2)][-1] == ids[-1]


def test_span_wire_codec_roundtrip():
    spans = [make_span("t" * 16, "s" * 16, None, "element:A", "element",
                       "p", "0", 7, 123.456, 1.25)]
    assert decode_spans(encode_spans(spans)) == spans
    assert decode_spans("not base64 json!") == []


# -- pipeline integration ---------------------------------------------------

def test_pipeline_telemetry_rollup_and_share(runtime):
    pipeline = simple_pipeline(
        runtime, parameters={"telemetry_interval": 0.0})
    pump(runtime, pipeline, 6)
    rollup = pipeline.telemetry.rollup()
    assert rollup["frame"]["count"] == 6
    assert rollup["frame"]["p50_ms"] > 0.0
    for name in ("A", "B"):
        entry = rollup["element"][name]
        assert entry["count"] == 6 and entry["p99_ms"] > 0.0
    assert rollup["counters"]["frames_total.ok"] == 6
    assert rollup["traces"]["completed"] == 6
    # published on the share dict for ECConsumer/Dashboard
    shared = pipeline.share["telemetry"]
    assert shared["frame"]["count"] >= 1
    assert "A" in shared["element"]
    pipeline.stop()


def test_metrics_text_nonzero_quantiles(runtime):
    pipeline = simple_pipeline(runtime)
    pump(runtime, pipeline, 5)
    text = pipeline.metrics_text()
    for name in ("A", "B"):
        for q in ("0.5", "0.99"):
            line = next(line for line in text.splitlines()
                        if line.startswith(
                            f'aiko_element_latency_ms{{element="{name}"'
                            f',quantile="{q}"}}'))
            assert float(line.split()[-1]) > 0.0
    assert "aiko_frames_processed 5" in text
    assert "aiko_traces_completed 5" in text
    pipeline.stop()


def test_telemetry_off_parameter(runtime):
    pipeline = simple_pipeline(runtime, name="p_off",
                               parameters={"telemetry": "off"})
    rows = pump(runtime, pipeline, 2)
    assert pipeline.telemetry is None
    assert pipeline.metrics_text() == ""
    assert pipeline.get_trace("anything") is None
    assert "telemetry" not in pipeline.share
    assert rows[0][4]                      # frames still flow
    pipeline.stop()


def test_frame_error_counted_and_traced(runtime):
    definition = {"version": 0, "name": "p_err", "runtime": "jax",
                  "graph": ["(A (B))"],
                  "elements": [element("A", "Increment"),
                               element("B", "Raiser",
                                       module="tests/pipeline_elements.py")]}
    definition["elements"][1]["input"] = [{"name": "x"}]
    pipeline = Pipeline(definition, runtime=runtime)
    responses = queue.Queue()
    pipeline.process_frame_local({"x": 1, "a": 1}, stream_id="s",
                                 queue_response=responses)
    assert run_until(runtime, lambda: not responses.empty())
    *_, okay, diagnostic = responses.get()
    assert not okay
    rollup = pipeline.telemetry.rollup()
    assert rollup["counters"]["frames_total.error"] == 1
    trace = pipeline.telemetry.traces.recent(1)[0]
    assert trace["okay"] is False
    root = next(s for s in trace["spans"] if s["kind"] == "frame")
    assert root["status"] == "error"
    pipeline.stop()


def test_metrics_snapshot_not_live_dict(runtime):
    """Responses must carry a SNAPSHOT of frame.metrics: consumers read
    from foreign threads and must never share the loop-confined live
    mapping."""
    pipeline = simple_pipeline(runtime, name="p_snap")
    stream = pipeline.create_stream_local("s")
    captured = {}
    original_respond = pipeline._respond

    def spy(stream, frame, okay, diagnostic=""):
        captured["frame"] = frame
        return original_respond(stream, frame, okay, diagnostic)

    pipeline._respond = spy
    rows = pump(runtime, pipeline, 1)
    returned_metrics = rows[0][3]
    assert returned_metrics is not captured["frame"].metrics
    assert returned_metrics == dict(captured["frame"].metrics)
    pipeline.stop()


def test_concurrent_metrics_scrape_under_load(runtime):
    """The export surface is read from foreign threads (HTTP) while the
    loop processes frames: no exception, and quantiles stay parseable."""
    pipeline = simple_pipeline(runtime, name="p_conc")
    errors = []
    stop = threading.Event()

    def scraper():
        while not stop.is_set():
            try:
                text = pipeline.metrics_text()
                assert text.startswith("#") or text == ""
                pipeline.telemetry.traces.recent(5)
            except Exception as error:      # pragma: no cover
                errors.append(error)
                return

    thread = threading.Thread(target=scraper)
    thread.start()
    try:
        for _ in range(4):
            pump(runtime, pipeline, 4)
    finally:
        stop.set()
        thread.join(timeout=5.0)
    assert not errors
    pipeline.stop()


def test_stream_destroy_purges_telemetry_state(runtime):
    """A destroyed stream's open/pending span state must not survive
    into a recreated same-id stream (frame ids restart per stream, so
    stale keys would graft dead spans onto fresh traces)."""
    definition = {"version": 0, "name": "p_purge", "runtime": "jax",
                  "graph": ["(A (S))"],
                  "elements": [element("A", "Increment"),
                               element("S", "SlowAsync",
                                       module="tests/test_stages.py")]}
    pipeline = Pipeline(definition, runtime=runtime)
    pipeline.create_stream_local("s")
    pipeline.ingest_local("s", {"x": 0})
    stream = pipeline.streams["s"]
    assert run_until(
        runtime,
        lambda: any(frame.paused_pe_name == "S"
                    for frame in stream.frames.values()),
        timeout=5.0)
    # Hard destroy with the frame parked at the async stage: its open
    # element span would otherwise linger under ("element","S","s",0).
    pipeline._destroy_stream_now("s")
    telemetry = pipeline.telemetry
    assert not any(key[2] == "s" for key in telemetry._open)
    assert not any(key[0] == "s" for key in telemetry._pending)
    # Recreated same-id stream: frame 0 again -- its trace must be
    # clean (no adopted stale spans, no "unclosed" ghosts).
    rows = pump(runtime, pipeline, 1, stream_id="s")
    assert rows[0][4]
    trace = telemetry.traces.recent(1)[0]
    assert all(span["trace_id"] == trace["trace_id"]
               for span in trace["spans"])
    assert all(span["status"] == "ok" for span in trace["spans"])
    pipeline.stop()


# -- HTTP export surface ----------------------------------------------------

def test_metrics_server_under_churn(runtime):
    """ISSUE 10 satellite: concurrent scrapes (/metrics + /traces)
    against a pipeline under stream churn AND a mid-flight device
    replacement -- every response is a 200 with a parseable body (no
    500s, no torn reads, no unbounded /traces bodies)."""
    definition = {
        "version": 0, "name": "p_churn", "runtime": "jax",
        "graph": ["(sa (sb))"],
        "elements": [
            {"name": name, "input": [{"name": "x"}],
             "output": [{"name": "x"}],
             "parameters": {"busy_ms": 2.0},
             "placement": {"mesh": {"dp": 4}},
             "deploy": {"local": {
                 "module": COMMON, "class_name": "StageWork"}}}
            for name in ("sa", "sb")]}
    import numpy as np

    pipeline = Pipeline(definition, runtime=runtime)
    server = MetricsServer(pipeline, port=0, host="127.0.0.1")
    base = f"http://127.0.0.1:{server.port}"
    errors, bodies = [], [0]
    stop = threading.Event()

    def scraper(path):
        while not stop.is_set():
            try:
                body = urllib.request.urlopen(f"{base}{path}",
                                              timeout=5.0).read()
                if path == "/metrics":
                    assert body.decode().startswith("#")
                elif path == "/traces":
                    payload = json.loads(body)
                    assert len(payload["traces"]) <= 50
                else:                       # /explain
                    payload = json.loads(body)
                    assert set(payload["buckets"]) and len(
                        payload.get("top", [])) <= 5
                bodies[0] += 1
            except Exception as error:      # pragma: no cover
                errors.append((path, error))
                return

    threads = [threading.Thread(target=scraper, args=(path,))
               for path in ("/metrics", "/traces", "/explain")]
    for thread in threads:
        thread.start()
    responses = queue.Queue()
    try:
        for round_index in range(3):
            stream_id = f"s{round_index}"
            for i in range(6):
                pipeline.process_frame_local(
                    {"x": np.float32(i)}, stream_id=stream_id,
                    queue_response=responses)
            if round_index == 1:
                # mid-flight replacement while scrapes continue
                dead = list(pipeline.stage_placement.plans["sa"]
                            .mesh.devices.flat)[:2]
                pipeline.post_self("replace_failed_devices", [dead],
                                   delay=0.005)
            assert run_until(runtime,
                             lambda: responses.qsize()
                             >= 6 * (round_index + 1), timeout=60.0)
            pipeline.post_self("destroy_stream", [stream_id])
            run_until(runtime,
                      lambda: stream_id not in pipeline.streams,
                      timeout=10.0)
    finally:
        stop.set()
        for thread in threads:
            thread.join(timeout=10.0)
        server.stop()
        pipeline.stop()
    assert not errors, errors
    assert bodies[0] > 0


def test_metrics_http_endpoint(runtime):
    pipeline = simple_pipeline(runtime, name="p_http")
    pump(runtime, pipeline, 3)
    server = MetricsServer(pipeline, port=0, host="127.0.0.1")
    try:
        base = f"http://127.0.0.1:{server.port}"
        text = urllib.request.urlopen(f"{base}/metrics",
                                      timeout=5.0).read().decode()
        assert "aiko_frame_latency_ms" in text
        assert "aiko_frames_processed 3" in text
        payload = json.loads(urllib.request.urlopen(
            f"{base}/traces?n=2", timeout=5.0).read())
        assert len(payload["traces"]) == 2
        trace_id = payload["traces"][-1]["trace_id"]
        one = json.loads(urllib.request.urlopen(
            f"{base}/traces/{trace_id}", timeout=5.0).read())
        assert one["trace_id"] == trace_id and one["spans"]
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{base}/nope", timeout=5.0)
        # n must be a positive integer: n=0 would slice [-0:] == all
        for bad in ("0", "-1", "abc"):
            with pytest.raises(urllib.error.HTTPError) as excinfo:
                urllib.request.urlopen(f"{base}/traces?n={bad}",
                                       timeout=5.0)
            assert excinfo.value.code == 400
    finally:
        server.stop()
        pipeline.stop()
