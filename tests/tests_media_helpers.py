"""Collector elements for media tests; SINK is swapped per-test."""

import numpy as np

from aiko_services_tpu.pipeline import PipelineElement, StreamEvent

SINK: list = []


class Collect(PipelineElement):
    def process_frame(self, stream, image=None, **inputs):
        SINK.append(np.asarray(image))
        return StreamEvent.OKAY, {}


class CollectSpectrum(PipelineElement):
    def process_frame(self, stream, spectrum=None, **inputs):
        SINK.append(np.asarray(spectrum))
        return StreamEvent.OKAY, {}


class CollectText(PipelineElement):
    def process_frame(self, stream, text=None, **inputs):
        SINK.append(text)
        return StreamEvent.OKAY, {}
