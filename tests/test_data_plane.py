"""Binary multi-host data plane (ISSUE 9): control/data split for
remote-stage frames -- tensors over the tensor pipe (negotiated via the
registrar record's ``tensor_pipe=`` tag), envelopes on MQTT -- plus the
pure-Python framing fallback, counted drops and fallbacks, the
never-lose-a-frame recovery on pipe death, distributed traces riding
the new path, and the ``mesh: {hosts: N}`` multi-host mesh mode."""

import json
import queue
import time

import jax.numpy as jnp
import numpy as np
import pytest

from conftest import run_until

from aiko_services_tpu.pipeline import Pipeline
from aiko_services_tpu.pipeline.data_plane import (PipeSender,
                                                   TensorPipeEndpoint,
                                                   split_arrays)
from aiko_services_tpu.pipeline.definition import DefinitionError
from aiko_services_tpu.services import Registrar
from aiko_services_tpu.transport.tensor_pipe import (
    PyTensorPipeClient, PyTensorPipeServer, TensorPipeClient,
    TensorPipeServer, create_pipe_client, create_pipe_server,
    native_pipe_available)

COMMON = "aiko_services_tpu.elements.common"


def element(name, cls, module=COMMON):
    return {"name": name, "input": [{"name": "x"}],
            "output": [{"name": "x"}],
            "deploy": {"local": {"module": module, "class_name": cls}}}


def remote(name, target):
    return {"name": name, "input": [{"name": "x"}],
            "output": [{"name": "x"}],
            "deploy": {"remote": {"name": target}}}


def remote_pair(runtime, front_params=None, back_params=None,
                back_cls="Identity"):
    Registrar(runtime=runtime, primary_search_timeout=0.05)
    back = Pipeline({"version": 0, "name": "back", "runtime": "jax",
                     "graph": ["(inc)"],
                     "parameters": dict(back_params or {}),
                     "elements": [element("inc", back_cls)]},
                    runtime=runtime)
    front = Pipeline({"version": 0, "name": "front", "runtime": "jax",
                      "graph": ["(fwd)"],
                      "parameters": dict(front_params or {}),
                      "elements": [remote("fwd", "back")]},
                     runtime=runtime)
    stage = front.graph.get_node("fwd").element
    assert run_until(runtime,
                     lambda: stage.remote_topic_path is not None,
                     timeout=10.0)
    return front, back, stage


def collect(runtime, responses, count, timeout=30.0):
    rows = []

    def drained():
        while not responses.empty():
            rows.append(responses.get())
        return len(rows) >= count

    run_until(runtime, drained, timeout=timeout)
    return rows


# -- pure-Python framing fallback (same wire format) ------------------------


def test_python_fallback_selected_and_round_trips(monkeypatch):
    monkeypatch.setenv("AIKO_TENSOR_PIPE_NATIVE", "0")
    assert not native_pipe_available()
    with create_pipe_server() as server:
        assert isinstance(server, PyTensorPipeServer)
        with create_pipe_client("127.0.0.1", server.port) as client:
            assert isinstance(client, PyTensorPipeClient)
            cases = [np.arange(24, dtype=np.int32).reshape(2, 3, 4),
                     np.zeros((0,), np.float64),
                     np.asarray(jnp.ones((4, 5), jnp.bfloat16))]
            for i, case in enumerate(cases):
                client.send(case, name=f"case{i}")
            for i, case in enumerate(cases):
                name, got = server.recv(timeout=5.0)
                assert name == f"case{i}"
                assert got.dtype == case.dtype
                assert got.shape == case.shape
                np.testing.assert_array_equal(got, case)


@pytest.mark.skipif(not native_pipe_available(),
                    reason="native tensor_pipe unavailable")
def test_python_framing_interops_with_native_both_directions():
    payload = np.arange(6, dtype=np.int16).reshape(2, 3)
    with TensorPipeServer() as server:
        with PyTensorPipeClient("127.0.0.1", server.port) as client:
            client.send(payload, name="py->c")
            name, got = server.recv(timeout=5.0)
            assert name == "py->c"
            np.testing.assert_array_equal(got, payload)
    with PyTensorPipeServer() as server:
        with TensorPipeClient("127.0.0.1", server.port) as client:
            client.send(payload, name="c->py")
            name, got = server.recv(timeout=5.0)
            assert name == "c->py"
            np.testing.assert_array_equal(got, payload)


def test_server_counts_drops_and_logs_first_per_connection():
    """Drop-oldest evictions are COUNTED (``server.dropped``), no
    longer silent -- the pipeline shares the number as
    ``tensor_pipe_dropped_frames``."""
    with create_pipe_server(queue_depth=2) as server:
        with create_pipe_client("127.0.0.1", server.port) as client:
            for i in range(10):
                client.send(np.asarray([i], np.int32))
            deadline = time.monotonic() + 5.0
            while server.dropped == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert server.dropped > 0
            # Newest survive, order preserved (the policy unchanged).
            survivors = []
            while True:
                frame = server.recv(timeout=0.5)
                if frame is None:
                    break
                survivors.append(int(frame[1][0]))
            assert survivors and survivors[-1] == 9
            assert survivors == sorted(survivors)


# -- endpoint claim/watch/expiry --------------------------------------------


def test_endpoint_claim_watch_and_expiry():
    endpoint = TensorPipeEndpoint(claim_timeout_s=0.3)
    try:
        sender = PipeSender(endpoint.location)
        arrays = {"x": np.arange(8, dtype=np.float32),
                  "b": np.asarray(jnp.ones((2, 2), jnp.bfloat16))}
        sent = sender.send("tok1", arrays)
        assert sent and sent > arrays["x"].nbytes
        deadline = time.monotonic() + 5.0
        claimed = None
        while claimed is None and time.monotonic() < deadline:
            claimed = endpoint.claim("tok1", ["x", "b"])
            time.sleep(0.01)
        assert claimed is not None
        np.testing.assert_array_equal(claimed["x"], arrays["x"])
        assert claimed["b"].dtype == jnp.bfloat16     # tag restored
        # A duplicate claim still answers (dup-envelope parity).
        assert endpoint.claim("tok1", ["x"]) is not None
        # Watch on a complete token fires inline.
        fired = []
        endpoint.watch("tok1", ["x"], lambda: fired.append("now"))
        assert fired == ["now"]
        # Watch on a token that never completes fires at the claim
        # timeout and counts the expiry.
        endpoint.watch("ghost", ["x"], lambda: fired.append("late"))
        deadline = time.monotonic() + 5.0
        while len(fired) < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert fired == ["now", "late"]
        assert endpoint.claims_expired == 1
        assert endpoint.claim("ghost", ["x"]) is None
        sender.close()
    finally:
        endpoint.close()


def test_split_arrays_matches_codec_predicate():
    data = {"image": np.zeros((2, 2)), "scalar": 3, "text": "hi",
            "flags": [1, 2], "np_scalar": np.float32(1.0)}
    assert sorted(split_arrays(data)) == ["image", "np_scalar"]


# -- remote hop over the pipe (negotiation, bytes, fallback) -----------------


def test_remote_hop_rides_pipe_and_counts(runtime):
    front, back, stage = remote_pair(runtime)
    assert stage.remote_pipe is not None          # negotiated via tag
    responses = queue.Queue()
    x = np.arange(256 * 256, dtype=np.uint8).reshape(256, 256)
    for _ in range(3):
        front.process_frame_local({"x": x}, stream_id="s",
                                  queue_response=responses)
    rows = collect(runtime, responses, 3)
    assert len(rows) == 3 and all(row[4] for row in rows), rows
    for row in rows:
        np.testing.assert_array_equal(np.asarray(row[2]["x"]), x)
    front_stats = front.data_plane_stats()
    back_stats = back.data_plane_stats()
    assert front_stats["pipe_frames"] == 3        # forwards
    assert back_stats["pipe_frames"] == 3         # responses
    assert front_stats["fallbacks"] == 0
    assert front.share["data_plane_frames"] == 3
    front.stop()
    back.stop()


def test_pipe_payload_byte_ratio_beats_base64(runtime):
    """The byte-tax acceptance: wire bytes per frame on the pipe path
    stay within 1.05x of the raw payload (forward + response), where
    the base64 MQTT path pays ~1.33x."""
    front, back, _ = remote_pair(runtime)
    responses = queue.Queue()
    x = np.random.default_rng(0).integers(
        0, 255, (512, 2048), dtype=np.uint8)      # 1 MB
    front.process_frame_local({"x": x}, stream_id="s",
                              queue_response=responses)
    rows = collect(runtime, responses, 1)
    assert rows and rows[0][4], rows
    fs, bs = front.data_plane_stats(), back.data_plane_stats()
    wire = fs["pipe_bytes"] + fs["mqtt_bytes"] \
        + bs["pipe_bytes"] + bs["mqtt_bytes"]
    assert wire / (2 * x.nbytes) <= 1.05, wire
    front.stop()
    back.stop()

    # Same frame forced onto MQTT: the base64 tax for contrast.
    mqtt_front, mqtt_back, _ = remote_pair_mqtt(runtime)
    mqtt_front.process_frame_local({"x": x}, stream_id="s",
                                   queue_response=responses)
    rows = collect(runtime, responses, 1)
    assert rows and rows[0][4], rows
    fs, bs = mqtt_front.data_plane_stats(), mqtt_back.data_plane_stats()
    wire = fs["mqtt_bytes"] + bs["mqtt_bytes"]
    assert wire / (2 * x.nbytes) >= 1.2, wire
    mqtt_front.stop()
    mqtt_back.stop()


def remote_pair_mqtt(runtime):
    back = Pipeline({"version": 0, "name": "back_m", "runtime": "jax",
                     "graph": ["(inc)"],
                     "parameters": {"data_plane": "mqtt"},
                     "elements": [element("inc", "Identity")]},
                    runtime=runtime)
    front = Pipeline({"version": 0, "name": "front_m", "runtime": "jax",
                      "graph": ["(fwd)"],
                      "parameters": {"data_plane": "mqtt"},
                      "elements": [remote("fwd", "back_m")]},
                     runtime=runtime)
    stage = front.graph.get_node("fwd").element
    assert run_until(runtime,
                     lambda: stage.remote_topic_path is not None,
                     timeout=10.0)
    return front, back, stage


def test_peer_without_pipe_negotiates_mqtt_counted(runtime):
    """A peer advertising no ``tensor_pipe=`` tag rides the MQTT
    payload path -- automatically, and COUNTED, never silent."""
    front, back, stage = remote_pair(
        runtime, back_params={"data_plane": "mqtt"})
    assert stage.remote_pipe is None              # nothing advertised
    responses = queue.Queue()
    x = np.arange(64, dtype=np.float32)
    front.process_frame_local({"x": x}, stream_id="s",
                              queue_response=responses)
    rows = collect(runtime, responses, 1)
    assert rows and rows[0][4], rows
    np.testing.assert_array_equal(np.asarray(rows[0][2]["x"]), x)
    stats = front.data_plane_stats()
    assert stats["pipe_frames"] == 0
    assert stats["fallbacks"] >= 1
    assert front.share["data_plane_fallbacks"] >= 1
    front.stop()
    back.stop()


def test_data_plane_mqtt_mode_binds_nothing(runtime):
    pipeline = Pipeline({"version": 0, "name": "p_mqtt",
                         "runtime": "jax", "graph": ["(inc)"],
                         "parameters": {"data_plane": "mqtt"},
                         "elements": [element("inc", "Increment")]},
                        runtime=runtime)
    assert pipeline._data_endpoint is None
    assert not any(tag.startswith("tensor_pipe=")
                   for tag in pipeline.tags)
    responses = queue.Queue()
    pipeline.process_frame_local({"x": 1}, stream_id="s",
                                 queue_response=responses)
    rows = collect(runtime, responses, 1)
    assert rows and rows[0][4] and int(rows[0][2]["x"]) == 2
    pipeline.stop()


# -- pipe death: fallback + recovery, never a lost frame ---------------------


def test_pipe_death_midstream_falls_back_and_completes_in_order(runtime):
    """ISSUE 9 acceptance: kill the remote's pipe endpoint mid-stream.
    Every subsequent frame still completes, in order -- either the
    send fails synchronously (immediate MQTT fallback) or the bytes
    die in a kernel buffer and the peer's claim timeout triggers the
    counted MQTT re-forward.  The stream never dies, no frame is
    lost."""
    front, back, _ = remote_pair(
        runtime,
        # Short claim timeout so the stranded-bytes recovery path runs
        # inside the test budget.
        back_params={"pipe_claim_timeout_ms": 400})
    responses = queue.Queue()
    x = np.arange(64 * 1024, dtype=np.uint8)
    front.process_frame_local({"x": x}, stream_id="s",
                              queue_response=responses)
    rows = collect(runtime, responses, 1)
    assert rows and rows[0][4], rows             # warm: pipe works
    assert front.data_plane_stats()["pipe_frames"] == 1

    back._data_endpoint.close()                  # the pipe dies
    for i in range(4):
        front.process_frame_local({"x": x + (i % 7)}, stream_id="s",
                                  queue_response=responses)
    rows = collect(runtime, responses, 4, timeout=60.0)
    assert len(rows) == 4
    assert all(row[4] for row in rows), \
        [row[5] for row in rows if not row[4]]
    # In order, values intact.
    for i, row in enumerate(rows):
        np.testing.assert_array_equal(np.asarray(row[2]["x"]),
                                      x + (i % 7))
    assert front.data_plane_stats()["fallbacks"] >= 1
    assert "s" in front.streams                  # stream alive
    front.stop()
    back.stop()


# -- distributed trace on the pipe path --------------------------------------


def test_trace_spans_both_processes_on_pipe_path(runtime):
    front, back, stage = remote_pair(runtime)
    assert stage.remote_pipe is not None
    responses = queue.Queue()
    front.process_frame_local({"x": np.arange(16, dtype=np.float32)},
                              stream_id="s", queue_response=responses)
    rows = collect(runtime, responses, 1)
    assert rows and rows[0][4], rows
    assert front.data_plane_stats()["pipe_frames"] == 1
    trace = front.telemetry.traces.recent(1)[0]
    spans = trace["spans"]
    assert {span["trace_id"] for span in spans} == {trace["trace_id"]}
    assert {span["process"] for span in spans} == {"front", "back"}
    hop = next(s for s in spans if s["name"] == "remote:fwd")
    remote_root = next(s for s in spans if s["kind"] == "frame"
                       and s["process"] == "back")
    assert remote_root["parent_id"] == hop["span_id"]
    front.stop()
    back.stop()


# -- multi-host mesh mode ----------------------------------------------------


def test_mesh_mode_carves_host_groups_and_serves(runtime):
    import jax

    n = len(jax.devices())
    assert n >= 4
    pipeline = Pipeline(
        {"version": 0, "name": "p_mesh", "runtime": "jax",
         "graph": ["(det llm)"],
         "parameters": {"mesh": {"hosts": 2}},
         "elements": [
             {"name": "det", "input": [{"name": "x"}],
              "output": [{"name": "x"}],
              "parameters": {"busy_ms": 1.0},
              "placement": {"devices": n // 2},
              "deploy": {"local": {"module": COMMON,
                                   "class_name": "StageWork"}}},
             {"name": "llm", "input": [{"name": "x"}],
              "output": [{"name": "x"}],
              "parameters": {"busy_ms": 1.0},
              "placement": {"devices": n // 2, "host": 1},
              "deploy": {"local": {"module": COMMON,
                                   "class_name": "StageWork"}}}]},
        runtime=runtime)
    placement = pipeline.stage_placement
    assert placement.hosts == 2
    assert [len(group) for group in placement.host_groups] == \
        [n - n // 2, n // 2]
    assert placement.stage_hosts == {"det": 0, "llm": 1}
    assert not placement.same_host("det", "llm")
    assert placement.stage_host("llm") == 1
    # Stages stay wholly inside their host group's devices.
    for stage, host in placement.stage_hosts.items():
        assert placement.stage_devices(stage) <= \
            set(placement.host_groups[host])
    # Frames flow across the cross-host hop (DCN through the shared
    # mesh -- placement.transfer, not the broker).
    responses = queue.Queue()
    x = np.ones((8, 8), dtype=np.float32)
    for _ in range(4):
        pipeline.process_frame_local({"x": x}, stream_id="s",
                                     queue_response=responses)
    rows = collect(runtime, responses, 4, timeout=60.0)
    assert len(rows) == 4 and all(row[4] for row in rows), rows
    assert placement.stats["stage_hosts"] == {"det": 0, "llm": 1}
    pipeline.stop()


def test_mesh_parameter_validation_at_create(runtime):
    broken = {"version": 0, "name": "p_mesh_bad", "runtime": "jax",
              "graph": ["(det)"],
              "parameters": {"mesh": {"hosts": 0}},
              "elements": [
                  {"name": "det", "input": [{"name": "x"}],
                   "output": [{"name": "x"}],
                   "placement": {"devices": 2},
                   "deploy": {"local": {"module": COMMON,
                                        "class_name": "StageWork"}}}]}
    with pytest.raises(DefinitionError, match="mesh"):
        Pipeline(broken, runtime=runtime)


def test_mesh_stage_that_spans_hosts_rejected(runtime):
    import jax

    n = len(jax.devices())
    broken = {"version": 0, "name": "p_mesh_span", "runtime": "jax",
              "graph": ["(det)"],
              # Lint would pass (the block is well-formed); the carve
              # itself must refuse a stage bigger than one host group.
              "parameters": {"mesh": {"hosts": 2}, "preflight": "off"},
              "elements": [
                  {"name": "det", "input": [{"name": "x"}],
                   "output": [{"name": "x"}],
                   "placement": {"devices": n},
                   "deploy": {"local": {"module": COMMON,
                                        "class_name": "StageWork"}}}]}
    with pytest.raises(DefinitionError, match="never spans hosts"):
        Pipeline(broken, runtime=runtime)


def test_placement_host_key_validated():
    from aiko_services_tpu.pipeline.definition import placement_error

    assert placement_error({"devices": 2, "host": 1}) is None
    assert "host" in placement_error({"devices": 2, "host": -1})
    assert "host" in placement_error({"devices": 2, "host": True})
    assert "host" in placement_error({"devices": 2, "host": "0"})


def test_mesh_env_spec(monkeypatch):
    from aiko_services_tpu.pipeline.tensor import distributed_mesh_spec

    monkeypatch.setenv("AIKO_MESH_HOSTS", "2")
    monkeypatch.setenv("AIKO_MESH_PROCESS_ID", "1")
    spec = distributed_mesh_spec({})
    assert spec["hosts"] == 2 and spec["process_id"] == 1
    # The pipeline parameter wins over the env.
    spec = distributed_mesh_spec({"mesh": {"hosts": 4}})
    assert spec["hosts"] == 4 and spec["process_id"] == 0


def test_py_server_tears_stalled_midframe_connection():
    """A peer that sends a frame prefix then stalls must not pin the
    reader forever (review hardening): the bounded mid-frame timeout
    tears the connection, and fresh connections keep working."""
    import socket
    import struct

    with PyTensorPipeServer() as server:
        server._BODY_TIMEOUT_S = 0.3
        raw = socket.create_connection(("127.0.0.1", server.port))
        raw.sendall(struct.pack("<IIQ", 0x54504950, 64, 128))
        time.sleep(1.0)              # reader gives up on the stall
        with PyTensorPipeClient("127.0.0.1", server.port) as client:
            client.send(np.asarray([5], np.int32), name="ok")
            frame = server.recv(timeout=5.0)
            assert frame is not None and frame[0] == "ok"
        raw.close()


def test_endpoint_counts_capacity_evictions():
    """Unclaimed tokens squeezed out by capacity pressure are COUNTED
    (review hardening): their envelopes pay the claim-timeout + MQTT
    re-forward, which must be visible, not a silent latency cliff."""
    endpoint = TensorPipeEndpoint(claim_timeout_s=5.0, capacity=2)
    try:
        sender = PipeSender(endpoint.location)
        for i in range(4):
            assert sender.send(f"tok{i}", {"x": np.asarray([i])})
        deadline = time.monotonic() + 5.0
        while endpoint.tokens_evicted < 2 \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        assert endpoint.tokens_evicted >= 2
        assert endpoint.stats["tokens_evicted"] >= 2
        # The newest tokens survived and still claim.
        assert endpoint.claim("tok3", ["x"]) is not None
        sender.close()
    finally:
        endpoint.close()
