"""Virtual 3-D world (examples/robot/virtual_world.py; reference
equivalent: examples/robot/virtual/world.py -- a 662-LoC Panda3D GUI
world).  The JAX raymarcher must produce a structurally sensible scene
(sky above, ground below, the red ball and the robot visible where the
camera looks), track the robot actor's share pose, and pump frames
through the real pipeline."""

import pathlib

import numpy as np

from conftest import run_until
from aiko_services_tpu.pipeline import Pipeline

ROBOT_DIR = pathlib.Path(__file__).resolve().parent.parent \
    / "examples" / "robot"


def load_world():
    # The framework importer's cache: binding a world here binds it for
    # the pipeline-loaded element too (same module object).
    from aiko_services_tpu.utils import load_module
    return load_module(str(ROBOT_DIR / "virtual_world.py"))


def small_world(module, **overrides):
    config = module.WorldConfig(width=64, height=48, **overrides)
    return module.VirtualWorld(config)


def test_render_structure():
    """Sky on the top rows, checkered ground on the bottom rows, red
    ball pixels where the ball sits."""
    module = load_world()
    world = small_world(module)
    image = world.camera_image("chase")
    assert image.shape == (48, 64, 3)
    assert np.isfinite(image).all()
    assert image.min() >= 0.0 and image.max() <= 1.0
    # Top rows are sky (blue channel dominates red).
    top = image[:4]
    assert float(top[..., 2].mean()) > float(top[..., 0].mean())
    # Bottom rows are lit checkerboard: two distinct ground tones.
    bottom = image[-8:]
    assert float(bottom.std()) > 0.02
    # The red ball is in front of the chase camera: some pixels are
    # strongly red-dominant.
    redness = image[..., 0] - jnp_max_other(image)
    assert float(redness.max()) > 0.25


def jnp_max_other(image):
    return np.maximum(image[..., 1], image[..., 2])


def test_robot_pose_changes_view():
    """Moving/turning the robot changes the rendered pixels, and the
    eye camera sees the ball only when facing it."""
    module = load_world()
    world = small_world(module)
    base = world.camera_image("chase")
    world.state.robot_xz = np.asarray([1.5, 0.5], np.float32)
    moved = world.camera_image("chase")
    assert float(np.abs(base - moved).mean()) > 0.005

    # Ball at (2.5, 0.5): face it from the origin -> red pixels; face
    # away -> none.
    world.state.robot_xz = np.asarray([0.0, 0.0], np.float32)
    world.state.robot_heading = np.arctan2(0.5, 2.5)
    facing = world.camera_image("eye")
    world.state.robot_heading += np.pi
    away = world.camera_image("eye")
    red_facing = float((facing[..., 0]
                        - jnp_max_other(facing)).max())
    red_away = float((away[..., 0] - jnp_max_other(away)).max())
    assert red_facing > 0.25
    assert red_away < 0.15


def test_world_syncs_robot_share():
    module = load_world()
    world = small_world(module)
    world.sync({"x": 2.0, "y": -1.0, "heading": 90.0})
    np.testing.assert_allclose(world.state.robot_xz, [2.0, -1.0])
    assert abs(world.state.robot_heading - np.pi / 2) < 1e-6


def test_world_camera_element_pumps_frames(runtime):
    """VirtualWorldCamera feeds rendered frames through the real
    pipeline, synced to a live VirtualRobot share (the robot moves,
    the rendered frames change)."""
    from test_robot_ooda import load_robot_actor

    module = load_world()
    robot = load_robot_actor().VirtualRobot(runtime=runtime)
    world = small_world(module)
    module.bind_world(world, robot.share)

    import tests_media_helpers
    collected = tests_media_helpers.SINK = []
    definition = {
        "version": 0, "name": "p_world", "runtime": "jax",
        "graph": ["(Cam Grab)"],
        "parameters": {},
        "elements": [
            {"name": "Cam", "input": [], "output": [{"name": "image"}],
             "deploy": {"local": {
                 "module": str(ROBOT_DIR / "virtual_world.py"),
                 "class_name": "VirtualWorldCamera"}},
             "parameters": {"camera": "chase", "frames": 3}},
            {"name": "Grab", "input": [{"name": "image"}], "output": [],
             "deploy": {"local": {"module": "tests_media_helpers",
                                  "class_name": "Collect"}},
             "parameters": {}},
        ]}
    pipeline = Pipeline(definition, runtime=runtime)
    pipeline.create_stream_local("s1")
    assert run_until(runtime, lambda: len(collected) >= 2, timeout=60.0)
    first = np.asarray(collected[0])
    assert first.shape == (48, 64, 3)

    # Move the robot: the synced world renders a different view.
    robot.share["x"] = 2.5
    robot.share["heading"] = 45.0
    world.sync(robot.share)
    after = world.camera_image("chase")
    assert float(np.abs(first - after).mean()) > 0.005
