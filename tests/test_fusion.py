"""Fused device-segment compilation (ISSUE 2): the partitioner, the
single-dispatch fused call, swag donation bookkeeping, the unfused
retry/resume fallback, and the env-gated persistent compile cache.

All fused-path pipelines here run under ``transfer_guard: disallow`` so
an implicit host sync inside a segment fails tier-1 fast -- the
acceptance criterion: one device dispatch per segment per frame, fused
outputs equal to unfused, zero ledger-counted host transfers inside a
segment.
"""

import json
import queue

import numpy as np

import jax
import jax.numpy as jnp

from conftest import run_until

from aiko_services_tpu.pipeline import (DeviceFn, FusedSegment,
                                        PipelineElement, StreamEvent,
                                        create_pipeline)
from aiko_services_tpu.pipeline import fusion


# -- fusable test elements ----------------------------------------------


class DeviceUpload(PipelineElement):
    device_resident = True

    def process_frame(self, stream, x=None, **inputs):
        return StreamEvent.OKAY, {"x": jnp.asarray(x)}

    def device_fn(self, stream):
        return DeviceFn(fn=lambda x: {"x": jnp.asarray(x)},
                        inputs=("x",), outputs=("x",))


class DeviceDouble(PipelineElement):
    device_resident = True

    def process_frame(self, stream, x=None, **inputs):
        return StreamEvent.OKAY, {"x": jnp.asarray(x) * 2}

    def device_fn(self, stream):
        return DeviceFn(fn=lambda x: {"x": jnp.asarray(x) * 2},
                        inputs=("x",), outputs=("x",))


class DeviceAddOne(PipelineElement):
    device_resident = True

    def process_frame(self, stream, x=None, **inputs):
        return StreamEvent.OKAY, {"x": jnp.asarray(x) + 1}

    def device_fn(self, stream):
        return DeviceFn(fn=lambda x: {"x": jnp.asarray(x) + 1},
                        inputs=("x",), outputs=("x",))


class DeviceNoFn(PipelineElement):
    """Device-resident but declares no device_fn: never fused."""

    device_resident = True

    def process_frame(self, stream, x=None, **inputs):
        return StreamEvent.OKAY, {"x": jnp.asarray(x) * 3}


class HostSink(PipelineElement):
    host_inputs = ("x",)

    def process_frame(self, stream, x=None, **inputs):
        return StreamEvent.OKAY, {"x": jnp.asarray(np.asarray(x) + 0.5)}


class AsyncDevice(PipelineElement):
    device_resident = True
    is_async = True

    def process_frame(self, stream, x=None, **inputs):
        return StreamEvent.OKAY, {"x": jnp.asarray(x) - 1}

    def process_frame_start(self, stream, complete, x=None, **inputs):
        complete(StreamEvent.OKAY, {"x": jnp.asarray(x) - 1})

    def device_fn(self, stream):
        # Declared fusable, but the async park path must still win
        # unless ``synchronous: true`` forces the blocking path.
        return DeviceFn(fn=lambda x: {"x": jnp.asarray(x) - 1},
                        inputs=("x",), outputs=("x",))


class BadTrace(PipelineElement):
    """device_fn whose trace fails (host sync on a tracer): the engine
    must poison the segment and fall back to per-element execution."""

    device_resident = True

    def process_frame(self, stream, x=None, **inputs):
        return StreamEvent.OKAY, {"x": jnp.asarray(x) * 5}

    def device_fn(self, stream):
        return DeviceFn(fn=lambda x: {"x": jnp.asarray(x) * float(x[0])},
                        inputs=("x",), outputs=("x",))


def _definition(tmp_path, elements, graph, parameters=None):
    body = {
        "version": 0, "name": "fusion", "runtime": "jax",
        "graph": graph, "parameters": parameters or {},
        "elements": [
            {"name": name,
             "input": [{"name": "x"}],
             "output": [{"name": "x"}],
             "parameters": params or {},
             "deploy": {"local": {"module": "test_fusion",
                                  "class_name": cls}}}
            for name, cls, params in elements]}
    path = tmp_path / "fusion.json"
    path.write_text(json.dumps(body))
    return str(path)


def _run_one(pipeline, runtime, value, stream_id="s"):
    responses = queue.Queue()
    stream = pipeline.create_stream_local(stream_id,
                                          queue_response=responses)
    pipeline.create_frame_local(stream, {"x": value})
    assert run_until(runtime, lambda: not responses.empty(), timeout=30.0)
    _, _, swag, metrics, okay, diagnostic = responses.get()
    return swag, metrics, okay, diagnostic


CHAIN = [("up", "DeviceUpload", {}), ("d1", "DeviceDouble", {}),
         ("d2", "DeviceDouble", {}), ("d3", "DeviceAddOne", {})]


# -- acceptance: one dispatch per segment, outputs equal, zero transfers -


def test_fused_chain_is_one_dispatch_and_matches_unfused(
        tmp_path, runtime):
    value = np.arange(8, dtype=np.float32)
    fused = create_pipeline(
        _definition(tmp_path, CHAIN, ["(up d1 d2 d3)"],
                    parameters={"transfer_guard": "disallow"}),
        runtime=runtime)
    swag, metrics, okay, diagnostic = _run_one(fused, runtime, value)
    assert okay, diagnostic
    # ONE device dispatch for the >=3-element device chain, below the
    # per-element count...
    assert metrics["device_dispatches"] == 1 < len(CHAIN)
    assert metrics["fused_segments"] == 1
    assert metrics["fused_elements"] == len(CHAIN)
    # ...with zero ledger-counted host transfers inside the segment...
    stats = fused.transfer_stats()
    assert stats["implicit"] == 0
    assert stats["explicit"] == 0
    assert isinstance(swag["x"], jax.Array)     # still device-resident
    fused.stop()

    unfused = create_pipeline(
        _definition(tmp_path, CHAIN, ["(up d1 d2 d3)"],
                    parameters={"transfer_guard": "disallow",
                                "fuse": "off"}),
        runtime=runtime)
    swag_off, metrics_off, okay_off, diagnostic_off = _run_one(
        unfused, runtime, value)
    assert okay_off, diagnostic_off
    # ...and fused outputs equal to unfused.
    np.testing.assert_array_equal(np.asarray(swag["x"]),
                                  np.asarray(swag_off["x"]))
    assert metrics_off["device_dispatches"] == len(CHAIN)
    assert "fused_segments" not in metrics_off
    unfused.stop()


def test_fused_share_and_jit_stats(tmp_path, runtime):
    pipeline = create_pipeline(
        _definition(tmp_path, CHAIN, ["(up d1 d2 d3)"]),
        runtime=runtime)
    responses = queue.Queue()
    stream = pipeline.create_stream_local("s", queue_response=responses)
    for i in range(3):
        pipeline.create_frame_local(
            stream, {"x": np.full(8, i, dtype=np.float32)})
    assert run_until(runtime, lambda: responses.qsize() >= 3,
                     timeout=30.0)
    # One compile (miss) then replays (hits), surfaced on the share
    # dict the dashboard reads and via jit_stats().
    stats = pipeline.jit_stats()
    segment_stats = list(stats["segments"].values())
    assert len(segment_stats) == 1
    assert segment_stats[0]["jit"]["misses"] == 1
    assert segment_stats[0]["jit"]["hits"] == 2
    assert segment_stats[0]["calls"] == 3
    assert pipeline.share["jit_cache_misses"] == stats["misses"]
    assert pipeline.share["jit_cache_entries"] >= 1
    assert pipeline.share["fused_segments"] == 1
    assert pipeline.share["fused_dispatches"] == 3
    pipeline.stop()


# -- partitioner boundaries ----------------------------------------------


def _partition_names(pipeline, stream_id="s"):
    """[entry names]: 'a+b' for segments, plain name for nodes."""
    stream = pipeline.create_stream_local(stream_id)
    pipeline._current_stream_ref = stream
    try:
        entries = fusion.partition(
            pipeline, pipeline.graph.get_path(stream.graph_path), stream)
    finally:
        pipeline._current_stream_ref = None
    return [entry.name for entry in entries]


def test_partitioner_host_input_boundary(tmp_path, runtime):
    pipeline = create_pipeline(
        _definition(tmp_path,
                    CHAIN[:2] + [("sink", "HostSink", {})]
                    + [("d4", "DeviceDouble", {}),
                       ("d5", "DeviceDouble", {})],
                    ["(up d1 sink d4 d5)"]),
        runtime=runtime)
    # The host-input sink splits the chain; both device runs fuse.
    assert _partition_names(pipeline) == ["up+d1", "sink", "d4+d5"]
    pipeline.stop()


def test_partitioner_microbatch_async_boundary(tmp_path, runtime):
    pipeline = create_pipeline(
        _definition(tmp_path,
                    CHAIN[:2] + [("a1", "AsyncDevice", {}),
                                 ("d4", "DeviceDouble", {}),
                                 ("d5", "DeviceDouble", {})],
                    ["(up d1 a1 d4 d5)"]),
        runtime=runtime)
    # The async (park/micro-batch) stage never joins a segment...
    assert _partition_names(pipeline) == ["up+d1", "a1", "d4+d5"]
    pipeline.stop()
    # ...unless synchronous: true forces its blocking path, which IS
    # fusable.
    sync = create_pipeline(
        _definition(tmp_path,
                    CHAIN[:2] + [("a1", "AsyncDevice",
                                  {"synchronous": True}),
                                 ("d4", "DeviceDouble", {})],
                    ["(up d1 a1 d4)"]),
        runtime=runtime)
    assert _partition_names(sync) == ["up+d1+a1+d4"]
    swag, metrics, okay, diagnostic = _run_one(
        sync, runtime, np.ones(4, dtype=np.float32), stream_id="s2")
    assert okay, diagnostic
    np.testing.assert_array_equal(np.asarray(swag["x"]),
                                  (np.ones(4) * 2 - 1) * 2)
    sync.stop()


def test_device_chain_after_async_park_still_fuses(tmp_path, runtime):
    """The async park site is a partition boundary: the resumed suffix
    re-enters the fused plan, so a device chain AFTER an async stage
    still executes as one dispatch (sharing the compiled segment with
    the full-path plan)."""
    pipeline = create_pipeline(
        _definition(tmp_path,
                    [("up", "DeviceUpload", {}),
                     ("a1", "AsyncDevice", {})] + CHAIN[1:],
                    ["(up a1 d1 d2 d3)"],
                    parameters={"transfer_guard": "disallow"}),
        runtime=runtime)
    value = np.arange(4, dtype=np.float32)
    swag, metrics, okay, diagnostic = _run_one(pipeline, runtime, value)
    assert okay, diagnostic
    np.testing.assert_array_equal(np.asarray(swag["x"]),
                                  (value - 1) * 2 * 2 + 1)
    assert metrics["fused_segments"] == 1           # d1+d2+d3, resumed
    assert metrics["fused_elements"] == 3
    # up (sync walk) + a1 (async submit) + the fused suffix = 3.
    assert metrics["device_dispatches"] == 3
    # One segment object serves both the full-path plan and the resume
    # suffix plan -- no duplicate compile.
    assert len(pipeline.fused_segments) == 1
    assert pipeline.fused_segments[0].calls == 1
    pipeline.stop()


def test_donation_blocked_for_mapped_qualified_reads(tmp_path, runtime):
    """A downstream node whose input mapping reads a producer-qualified
    key (``pre.x``) pins that buffer: the segment must never donate
    it, or the consumer would see a dead buffer after the alias pop."""
    pipeline = create_pipeline(
        _definition(tmp_path, CHAIN, ["(up d1 d2 d3)"]),
        runtime=runtime)
    stream = pipeline.create_stream_local("s")
    entries = pipeline._fusion_entries(
        stream, pipeline.graph.get_path(None))
    segment = next(e for e in entries if isinstance(e, FusedSegment))
    segment.donation = True                     # as on TPU/GPU
    value = jnp.arange(4, dtype=jnp.float32)
    resolved = {"x": value}
    swag = {"x": value, "pre.x": value}
    assert segment.donate_keys(resolved, swag, {"x": "pre"}) == {"x"}
    # The same key with its qualified alias named by a graph mapping:
    # blocked.
    segment._qualified_reads = frozenset({"pre.x"})
    assert segment.donate_keys(resolved, swag, {"x": "pre"}) == set()
    pipeline.stop()


def test_partitioner_single_nodes_stay_unfused(tmp_path, runtime):
    """A lone fusable node between boundaries gains nothing from a
    one-element 'segment'; it stays a plain per-element dispatch.  An
    element without a device_fn is a boundary too (the wire-sink /
    opaque element case)."""
    pipeline = create_pipeline(
        _definition(tmp_path,
                    [("up", "DeviceUpload", {}),
                     ("o1", "DeviceNoFn", {}),
                     ("d1", "DeviceDouble", {}),
                     ("o2", "DeviceNoFn", {}),
                     ("d2", "DeviceDouble", {}),
                     ("d3", "DeviceDouble", {})],
                    ["(up o1 d1 o2 d2 d3)"]),
        runtime=runtime)
    assert _partition_names(pipeline) == ["up", "o1", "d1", "o2", "d2+d3"]
    swag, metrics, okay, diagnostic = _run_one(
        pipeline, runtime, np.ones(4, dtype=np.float32),
        stream_id="s2")
    assert okay, diagnostic
    np.testing.assert_array_equal(np.asarray(swag["x"]),
                                  np.ones(4) * 3 * 2 * 3 * 2 * 2)
    pipeline.stop()


def test_fuse_off_parameter_disables_partitioning(tmp_path, runtime):
    pipeline = create_pipeline(
        _definition(tmp_path, CHAIN, ["(up d1 d2 d3)"],
                    parameters={"fuse": "off"}),
        runtime=runtime)
    _run_one(pipeline, runtime, np.ones(4, dtype=np.float32))
    assert pipeline.fusion_stats()["segments"] == 0
    pipeline.stop()


# -- donation bookkeeping and replay safety ------------------------------


def test_donation_does_not_corrupt_retry_replays(tmp_path, runtime):
    """A frame that ran fused (donating eligible swag intermediates)
    must replay cleanly through the unfused retry path: the swag holds
    only live buffers afterwards, and the replayed outputs match."""
    elements = [("up", "DeviceUpload", {}), ("pre", "DeviceNoFn", {})] \
        + CHAIN[1:]
    pipeline = create_pipeline(
        _definition(tmp_path, elements, ["(up pre d1 d2 d3)"],
                    parameters={"transfer_guard": "disallow"}),
        runtime=runtime)
    responses = queue.Queue()
    stream = pipeline.create_stream_local("s", queue_response=responses)
    value = np.arange(4, dtype=np.float32)
    pipeline.create_frame_local(stream, {"x": value})
    assert run_until(runtime, lambda: not responses.empty(), timeout=30.0)
    _, frame_id, swag, metrics, okay, diagnostic = responses.get()
    assert okay, diagnostic
    expected = value * 3 * 2 * 2 + 1
    np.testing.assert_array_equal(np.asarray(swag["x"]), expected)
    assert metrics["fused_segments"] == 1       # d1+d2+d3 fused
    # Every swag leaf is still materializable (no dangling donated
    # buffer survived map-out).
    for key, leaf in swag.items():
        np.asarray(leaf)

    # Unfused replay of the same frame from scratch: same result.
    from aiko_services_tpu.pipeline.stream import Frame
    replay = Frame(frame_id=99, swag={"x": value})
    pipeline.retry_frame("s", replay)
    assert run_until(runtime, lambda: not responses.empty(), timeout=30.0)
    _, _, swag2, metrics2, okay2, diagnostic2 = responses.get()
    assert okay2, diagnostic2
    assert "fused_segments" not in metrics2     # retry path is unfused
    np.testing.assert_array_equal(np.asarray(swag2["x"]), expected)
    pipeline.stop()


def test_retry_frame_at_resumes_unfused_mid_chain(tmp_path, runtime):
    pipeline = create_pipeline(
        _definition(tmp_path, CHAIN, ["(up d1 d2 d3)"]),
        runtime=runtime)
    responses = queue.Queue()
    stream = pipeline.create_stream_local("s", queue_response=responses)
    value = np.arange(4, dtype=np.float32)
    pipeline.create_frame_local(stream, {"x": value})
    assert run_until(runtime, lambda: not responses.empty(), timeout=30.0)
    responses.get()

    # Resume a frame mid-(would-be-)segment: per-element execution,
    # correct continuation from the existing swag.
    from aiko_services_tpu.pipeline.stream import Frame
    frame = Frame(frame_id=7, swag={"x": jnp.asarray(value)})
    pipeline.retry_frame_at("s", frame, "d2")
    assert run_until(runtime, lambda: not responses.empty(), timeout=30.0)
    _, _, swag, metrics, okay, diagnostic = responses.get()
    assert okay, diagnostic
    assert "fused_segments" not in metrics
    np.testing.assert_array_equal(np.asarray(swag["x"]), value * 2 + 1)
    pipeline.stop()


def test_broken_trace_falls_back_to_per_element(tmp_path, runtime):
    """A device_fn that lies about purity (host sync on a tracer) must
    not take the frame down: the segment poisons itself and the chain
    runs per-element, every frame, with correct outputs."""
    pipeline = create_pipeline(
        _definition(tmp_path,
                    [("up", "DeviceUpload", {}),
                     ("bad", "BadTrace", {}),
                     ("d1", "DeviceDouble", {})],
                    ["(up bad d1)"]),
        runtime=runtime)
    value = np.full(4, 2.0, dtype=np.float32)
    swag, metrics, okay, diagnostic = _run_one(pipeline, runtime, value)
    assert okay, diagnostic
    np.testing.assert_array_equal(np.asarray(swag["x"]), value * 5 * 2)
    assert pipeline.fusion_stats()["broken"] == 1
    # Later frames skip the poisoned segment without re-failing.
    responses = queue.Queue()
    stream = pipeline.streams["s"]
    stream.queue_response = responses
    pipeline.create_frame_local(stream, {"x": value})
    assert run_until(runtime, lambda: not responses.empty(), timeout=30.0)
    *_, okay2, diagnostic2 = responses.get()
    assert okay2, diagnostic2
    pipeline.stop()


# -- real elements: fused vs unfused equality ----------------------------


def _media_definition(tmp_path, parameters=None):
    """Two synchronous ImageResizes + a synchronous Detector -- the real
    device chain (image elements + detect), config4's DET leg run
    synchronously so it fuses."""
    body = {
        "version": 0, "name": "fusion_media", "runtime": "jax",
        "graph": ["(R1 (R2 (DET)))"],
        "parameters": parameters or {},
        "elements": [
            {"name": "R1", "input": [{"name": "image"}],
             "output": [{"name": "image"}],
             "parameters": {"width": 32, "height": 32,
                            "synchronous": True},
             "deploy": {"local": {
                 "module": "aiko_services_tpu.elements.image",
                 "class_name": "ImageResize"}}},
            {"name": "R2", "input": [{"name": "image"}],
             "output": [{"name": "image"}],
             "parameters": {"width": 16, "height": 16,
                            "synchronous": True},
             "deploy": {"local": {
                 "module": "aiko_services_tpu.elements.image",
                 "class_name": "ImageResize"}}},
            {"name": "DET", "input": [{"name": "image"}],
             "output": [{"name": "image"}, {"name": "overlay"},
                        {"name": "detections"}],
             "parameters": {"width": 4, "synchronous": True},
             "deploy": {"local": {
                 "module": "aiko_services_tpu.elements.detect",
                 "class_name": "Detector"}}},
        ]}
    path = tmp_path / "fusion_media.json"
    path.write_text(json.dumps(body))
    return str(path)


def test_media_chain_fused_matches_unfused(tmp_path, runtime):
    """The real ImageResize->ImageResize->Detector chain: fused under
    ``transfer_guard: disallow`` (the Detector's slate fetch rides the
    engine's ONE counted finalize fetch), outputs identical to the
    ``fuse: off`` walk."""
    rng = np.random.default_rng(3)
    image = rng.integers(0, 255, (64, 64, 3)).astype(np.uint8)

    fused = create_pipeline(
        _media_definition(tmp_path, {"transfer_guard": "disallow"}),
        runtime=runtime)
    responses = queue.Queue()
    stream = fused.create_stream_local("sf", queue_response=responses)
    fused.create_frame_local(stream, {"image": image})
    assert run_until(runtime, lambda: not responses.empty(), timeout=60.0)
    _, _, swag, metrics, okay, diagnostic = responses.get()
    assert okay, diagnostic
    assert metrics.get("fused_segments") == 1
    assert metrics["device_dispatches"] == 1
    # The Detector finalize paid exactly ONE counted fetch.
    assert fused.transfer_stats()["explicit"] == 1
    assert fused.transfer_stats()["implicit"] == 0
    fused.stop()

    unfused = create_pipeline(
        _media_definition(tmp_path, {"fuse": "off"}),
        runtime=runtime)
    responses = queue.Queue()
    stream = unfused.create_stream_local("su", queue_response=responses)
    unfused.create_frame_local(stream, {"image": image})
    assert run_until(runtime, lambda: not responses.empty(), timeout=60.0)
    _, _, swag_off, _, okay_off, diagnostic_off = responses.get()
    assert okay_off, diagnostic_off
    np.testing.assert_array_equal(np.asarray(swag["image"]),
                                  np.asarray(swag_off["image"]))
    assert swag["detections"] == swag_off["detections"]
    assert swag["overlay"] == swag_off["overlay"]
    unfused.stop()


def test_audio_fft_passthrough_preserves_host_types(tmp_path, runtime):
    """sample_rate rides AROUND the trace: after a fused AudioFFT the
    swag's sample_rate is still the plain int the unfused path keeps."""
    body = {
        "version": 0, "name": "fusion_fft", "runtime": "jax",
        "graph": ["(FFT)"], "parameters": {},
        "elements": [
            {"name": "FFT",
             "input": [{"name": "frames"}, {"name": "sample_rate"}],
             "output": [{"name": "spectrum"}, {"name": "sample_rate"}],
             "parameters": {"synchronous": True},
             "deploy": {"local": {
                 "module": "aiko_services_tpu.elements.audio",
                 "class_name": "AudioFFT"}}}]}
    path = tmp_path / "fusion_fft.json"
    path.write_text(json.dumps(body))
    pipeline = create_pipeline(str(path), runtime=runtime)
    stream = pipeline.create_stream_local("s")
    entries = pipeline._fusion_entries(
        stream, pipeline.graph.get_path(None))
    # A single element never forms a segment; the passthrough contract
    # is exercised through a 2-element chain below instead.
    assert all(not isinstance(entry, FusedSegment) for entry in entries)
    pipeline.stop()


def test_fft_chain_passthrough_sample_rate(tmp_path, runtime):
    body = {
        "version": 0, "name": "fusion_fft2", "runtime": "jax",
        "graph": ["(FR (FFT))"], "parameters": {},
        "elements": [
            {"name": "FR",
             "input": [{"name": "audio"}, {"name": "sample_rate"}],
             "output": [{"name": "frames"}, {"name": "sample_rate"}],
             "parameters": {"window": 16, "hop": 8},
             "deploy": {"local": {
                 "module": "aiko_services_tpu.elements.audio",
                 "class_name": "AudioFraming"}}},
            {"name": "FFT",
             "input": [{"name": "frames"}, {"name": "sample_rate"}],
             "output": [{"name": "spectrum"}, {"name": "sample_rate"}],
             "parameters": {"synchronous": True},
             "deploy": {"local": {
                 "module": "aiko_services_tpu.elements.audio",
                 "class_name": "AudioFFT"}}}]}
    path = tmp_path / "fusion_fft2.json"
    path.write_text(json.dumps(body))
    pipeline = create_pipeline(str(path), runtime=runtime)
    responses = queue.Queue()
    stream = pipeline.create_stream_local("s", queue_response=responses)
    audio = np.sin(np.linspace(0, 20, 64)).astype(np.float32)
    pipeline.create_frame_local(stream,
                                {"audio": audio, "sample_rate": 8000})
    assert run_until(runtime, lambda: not responses.empty(), timeout=30.0)
    _, _, swag, metrics, okay, diagnostic = responses.get()
    assert okay, diagnostic
    assert swag["sample_rate"] == 8000
    assert isinstance(swag["sample_rate"], int)     # type preserved
    element = pipeline.graph.get_node("FFT").element
    _, sync_out = element.process_frame(
        None, frames=np.asarray(swag["frames"]))
    np.testing.assert_allclose(np.asarray(swag["spectrum"]),
                               np.asarray(sync_out["spectrum"]),
                               rtol=1e-5, atol=1e-5)
    pipeline.stop()


# -- config4 graph: fused vs unfused outputs equal -----------------------


def _config4_definition(tmp_path, parameters):
    definition = {
        "version": 0, "name": "config4_fuse", "runtime": "jax",
        "graph": ["(DET (CAP (LLM)))"],
        "parameters": parameters,
        "elements": [
            {"name": "DET",
             "input": [{"name": "image"}],
             "output": [{"name": "image"}, {"name": "overlay"},
                        {"name": "detections"}],
             "parameters": {"width": 4, "synchronous": True},
             "deploy": {"local": {
                 "module": "aiko_services_tpu.elements.detect",
                 "class_name": "Detector"}}},
            {"name": "CAP",
             "input": [{"name": "detections"}],
             "output": [{"name": "text"}],
             "deploy": {"local": {
                 "module": "aiko_services_tpu.elements.llm",
                 "class_name": "DetectionCaption"}}},
            {"name": "LLM",
             "input": [{"name": "text"}],
             "output": [{"name": "text"}],
             "parameters": {"max_new_tokens": 4, "max_seq": 64,
                            "synchronous": True},
             "deploy": {"local": {
                 "module": "aiko_services_tpu.elements.llm",
                 "class_name": "LLM"}}},
        ]}
    path = tmp_path / "config4_fuse.json"
    path.write_text(json.dumps(definition))
    return str(path)


def test_config4_fused_matches_unfused(tmp_path, runtime):
    """The config-4 composition under ``fuse: auto`` vs ``fuse: off``:
    identical outputs.  (Nothing in this graph is legal to fuse -- DET
    finalizes host detections consumed by the host CAP, the LLM is a
    host-text stage -- so auto mode's whole job here is to decline
    correctly.)"""
    rng = np.random.default_rng(0)
    image = rng.integers(0, 255, (64, 64, 3), dtype=np.uint8)
    texts = {}
    for mode in ("auto", "off"):
        pipeline = create_pipeline(
            _config4_definition(tmp_path, {"fuse": mode}),
            runtime=runtime)
        responses = queue.Queue()
        stream = pipeline.create_stream_local(
            f"s_{mode}", queue_response=responses)
        pipeline.create_frame_local(stream, {"image": image.copy()})
        assert run_until(runtime, lambda: not responses.empty(),
                         timeout=300.0)
        _, _, swag, _, okay, diagnostic = responses.get()
        assert okay, diagnostic
        texts[mode] = (swag["text"], swag["detections"])
        pipeline.stop()
    assert texts["auto"] == texts["off"]


def test_donate_keys_eligibility(tmp_path, runtime):
    """Donation bookkeeping (unit; actual donation is TPU/GPU-only):
    only frame-produced, segment-overwritten, unaliased swag arrays
    qualify -- ingest/user data and externally-aliased values never
    do."""
    pipeline = create_pipeline(
        _definition(tmp_path, CHAIN, ["(up d1 d2 d3)"]),
        runtime=runtime)
    stream = pipeline.create_stream_local("s")
    entries = pipeline._fusion_entries(
        stream, pipeline.graph.get_path(None))
    segment = next(e for e in entries if isinstance(e, FusedSegment))
    segment.donation = True                 # as on TPU/GPU
    value = jnp.arange(4, dtype=jnp.float32)
    resolved = {"x": value}

    # Ingest/user-supplied value (no provenance): never donated.
    assert segment.donate_keys(resolved, {"x": value}, {}) == set()
    # Produced by an earlier element, overwritten by the segment, only
    # the bare + producer-qualified aliases in the swag: donatable.
    swag = {"x": value, "pre.x": value}
    assert segment.donate_keys(resolved, swag, {"x": "pre"}) == {"x"}
    # A third alias elsewhere in the swag blocks donation.
    swag["kept_copy"] = value
    assert segment.donate_keys(resolved, swag, {"x": "pre"}) == set()
    # Host values never donate.
    host = {"x": np.arange(4, dtype=np.float32)}
    assert segment.donate_keys(
        host, {"x": host["x"], "pre.x": host["x"]}, {"x": "pre"}) == set()
    pipeline.stop()


# -- profiler: segment + compile spans -----------------------------------


def test_segment_hooks_flag_first_use_compile(tmp_path, runtime):
    """The engine fires segment enter/post hooks around the single
    dispatch, flagging the first-use trace (``compile: True``) so the
    profiler can annotate first-frame compile time separately from
    steady-state steps; the Profiler keeps every span balanced."""
    from aiko_services_tpu.tpu import Profiler

    pipeline = create_pipeline(
        _definition(tmp_path, CHAIN, ["(up d1 d2 d3)"]),
        runtime=runtime)
    seen = []
    pipeline.add_hook_handler(
        "pipeline.process_segment:0",
        lambda component, hook, variables: seen.append(dict(variables)))
    profiler = Profiler()
    profiler.attach(pipeline)
    try:
        responses = queue.Queue()
        stream = pipeline.create_stream_local(
            "s", queue_response=responses)
        for i in range(2):
            pipeline.create_frame_local(
                stream, {"x": np.full(4, i, dtype=np.float32)})
        assert run_until(runtime, lambda: responses.qsize() >= 2,
                         timeout=30.0)
    finally:
        profiler.detach()
    assert not profiler._open               # every span closed
    assert [entry["compile"] for entry in seen] == [True, False]
    assert seen[0]["segment"] == "up+d1+d2+d3"
    assert seen[0]["elements"] == ["up", "d1", "d2", "d3"]
    post = pipeline._hooks["pipeline.process_segment_post:0"]
    assert post.count == 2
    pipeline.stop()


# -- persistent compile cache --------------------------------------------


def test_compilation_cache_env_gated(tmp_path, monkeypatch):
    import jax as _jax
    from aiko_services_tpu.pipeline import fusion as fusion_module
    monkeypatch.setattr(fusion_module, "_CACHE_DIR_CONFIGURED", None)
    # Absent the gate: nothing configured.
    monkeypatch.delenv("AIKO_COMPILE_CACHE_DIR", raising=False)
    assert fusion_module.setup_compilation_cache({}) is None
    # Gated on: the directory is created and jax config points at it.
    target = tmp_path / "xla_cache"
    monkeypatch.setenv("AIKO_COMPILE_CACHE_DIR", str(target))
    assert fusion_module.setup_compilation_cache({}) == str(target)
    assert target.is_dir()
    assert _jax.config.jax_compilation_cache_dir == str(target)
    # Idempotent: a second pipeline with a different parameter dir does
    # not re-point the process-global cache.
    assert fusion_module.setup_compilation_cache(
        {"compile_cache_dir": str(tmp_path / "other")}) == str(target)
    monkeypatch.setattr(fusion_module, "_CACHE_DIR_CONFIGURED", None)
    _jax.config.update("jax_compilation_cache_dir", None)
