"""Pipeline engine tests: definitions, graph name mapping (reference
tests/unit/test_pipeline_graph.py matrix), stream events (reference
tests/unit/test_stream_event.py), loops, remote two-pipeline chaining."""

import json
import queue

import pytest

from conftest import run_until
from aiko_services_tpu.pipeline import (
    Pipeline, parse_pipeline_definition, DefinitionError, StreamState)

ELEMENTS = "tests/pipeline_elements.py"


def element(name, cls, inputs, outputs, parameters=None):
    return {"name": name,
            "input": [{"name": n} for n in inputs],
            "output": [{"name": n} for n in outputs],
            "deploy": {"local": {"module": ELEMENTS, "class_name": cls}},
            "parameters": parameters or {}}


def definition(graph, elements, name="p_test", parameters=None):
    return {"version": 0, "name": name, "runtime": "jax", "graph": graph,
            "parameters": parameters or {}, "elements": elements}


def run_frame(runtime, pipeline, frame_data, timeout=5.0):
    responses = queue.Queue()
    pipeline.process_frame_local(frame_data, queue_response=responses)
    run_until(runtime, lambda: not responses.empty(), timeout=timeout)
    assert not responses.empty(), "no response (frame lost?)"
    stream_id, frame_id, swag, metrics, okay, diagnostic = responses.get()
    return swag, okay, diagnostic


# -- definition validation --------------------------------------------------

def test_definition_validation_errors():
    with pytest.raises(DefinitionError, match="missing required"):
        parse_pipeline_definition({"version": 0})
    with pytest.raises(DefinitionError, match="runtime"):
        parse_pipeline_definition(
            {"name": "x", "runtime": "cuda", "graph": ["(a)"],
             "elements": []})
    with pytest.raises(DefinitionError, match="duplicate"):
        parse_pipeline_definition(definition(
            ["(A A)"], [element("A", "ElementA", ["a"], ["a"]),
                        element("A", "ElementA", ["a"], ["a"])]))
    with pytest.raises(DefinitionError, match="deploy"):
        parse_pipeline_definition(definition(
            ["(A)"], [{"name": "A", "input": [], "output": []}]))


def test_unknown_graph_element_rejected():
    with pytest.raises(DefinitionError, match="no element definition"):
        Pipeline(definition(["(A B)"],
                            [element("A", "ElementA", ["a"], ["a"])]))


# -- graph name-mapping matrix (reference test_pipeline_graph.py) -----------

def test_linear_positional_mapping(runtime):
    """B consumes A's output by bare name."""
    p = Pipeline(definition(
        ["(A B C)"],
        [element("A", "ElementA", ["a"], ["a"]),
         element("B", "ElementB", ["a"], ["b"]),
         element("C", "ElementC", ["b"], ["c"])]), runtime=runtime)
    swag, okay, _ = run_frame(runtime, p, {"a": 1})
    assert okay
    assert swag["a"] == 1 and swag["b"] == 2 and swag["c"] == 4
    assert swag["B.b"] == 2 and swag["C.c"] == 4


def test_qualified_mapping(runtime):
    """C's input b mapped from qualified A.a: c = a*2, ignoring B."""
    p = Pipeline(definition(
        ["(A B (C (b: A.a)))"],
        [element("A", "ElementA", ["a"], ["a"]),
         element("B", "ElementB", ["a"], ["b"]),
         element("C", "ElementC", ["b"], ["c"])]), runtime=runtime)
    swag, okay, _ = run_frame(runtime, p, {"a": 10})
    assert okay
    assert swag["c"] == 20          # from a=10, not b=11


def test_renamed_input_mapping(runtime):
    """Doubler input x mapped from swag value a."""
    p = Pipeline(definition(
        ["(A (D (x: a)))"],
        [element("A", "ElementA", ["a"], ["a"]),
         element("D", "Doubler", ["x"], ["x"])]), runtime=runtime)
    swag, okay, _ = run_frame(runtime, p, {"a": 7})
    assert okay and swag["x"] == 14


def test_fanout_fanin_diamond(runtime):
    """(A (B D) (C D)): DFS order A B D C; D runs once after B."""
    p = Pipeline(definition(
        ["(A (B D) (C (b: a) D))"],
        [element("A", "ElementA", ["a"], ["a"]),
         element("B", "ElementB", ["a"], ["b"]),
         element("C", "ElementC", ["b"], ["c"]),
         element("D", "AddOne", ["x"], ["x"],)]),
        runtime=runtime)
    # D needs input x; map from b via graph properties
    p2 = Pipeline(definition(
        ["(A (B (D (x: b))) (C (b: a)))"],
        [element("A", "ElementA", ["a"], ["a"]),
         element("B", "ElementB", ["a"], ["b"]),
         element("C", "ElementC", ["b"], ["c"]),
         element("D", "AddOne", ["x"], ["x"])]), name="p2",
        runtime=runtime)
    swag, okay, _ = run_frame(runtime, p2, {"a": 1})
    assert okay
    assert swag["b"] == 2           # B
    assert swag["x"] == 3           # D = b+1
    assert swag["c"] == 2           # C from mapped a=1


def test_missing_input_is_frame_error(runtime):
    p = Pipeline(definition(
        ["(B)"], [element("B", "ElementB", ["a"], ["b"])]),
        runtime=runtime)
    swag, okay, diagnostic = run_frame(runtime, p, {"zzz": 1})
    assert not okay and "missing inputs" in diagnostic


# -- stream events ----------------------------------------------------------

def test_error_event_destroys_stream_no_deadlock(runtime):
    """Reference regression PR #32: ERROR must not deadlock the stream."""
    p = Pipeline(definition(
        ["(A F)"],
        [element("A", "ElementA", ["a"], ["a"]),
         element("F", "Failer", [], [])]), runtime=runtime)
    swag, okay, diagnostic = run_frame(runtime, p, {"a": 1})
    assert not okay and "deliberate failure" in diagnostic
    # Stream destroyed; a new frame starts a fresh stream and also errors.
    swag, okay, _ = run_frame(runtime, p, {"a": 2})
    assert not okay


def test_element_exception_is_frame_error(runtime):
    p = Pipeline(definition(
        ["(R)"], [element("R", "Raiser", [], [])]), runtime=runtime)
    swag, okay, diagnostic = run_frame(runtime, p, {})
    assert not okay and "exploded" in diagnostic


def test_stop_event_ends_stream(runtime):
    p = Pipeline(definition(
        ["(A S)"],
        [element("A", "ElementA", ["a"], ["a"]),
         element("S", "Stopper", [], [])]), runtime=runtime)
    swag, okay, _ = run_frame(runtime, p, {"a": 1})
    assert okay
    run_until(runtime, lambda: not p.streams, timeout=5.0)
    assert not p.streams


# -- loops ------------------------------------------------------------------

def test_loop_element(runtime):
    p = Pipeline(definition(
        ["(CNT LOOP)"],
        [element("CNT", "Counter", ["n"], ["n"]),
         {"name": "LOOP", "input": [], "output": [],
          "deploy": {"local": {
              "module": "aiko_services_tpu.elements.control",
              "class_name": "Loop"}},
          "parameters": {"condition": "n < 5", "loop_start": "CNT"}}]),
        runtime=runtime)
    swag, okay, _ = run_frame(runtime, p, {"n": 0})
    assert okay
    assert swag["n"] == 5


# -- remote two-pipeline chaining (reference multitude, in one process) -----

def test_remote_stage_chaining(runtime):
    from aiko_services_tpu.services import Registrar
    registrar = Registrar(runtime=runtime, primary_search_timeout=0.05)

    child = Pipeline(definition(
        ["(D2)"], [element("D2", "Doubler", ["x"], ["x"])],
        name="p_child"), runtime=runtime)

    parent_def = definition(
        ["(A (REMOTE (x: a)) (INC (x: REMOTE.x)))"],
        [element("A", "ElementA", ["a"], ["a"]),
         {"name": "REMOTE",
          "input": [{"name": "x"}], "output": [{"name": "x"}],
          "deploy": {"remote": {"name": "p_child"}}},
         element("INC", "AddOne", ["x"], ["x"])],
        name="p_parent")
    parent = Pipeline(parent_def, runtime=runtime)

    remote_stage = parent.graph.get_node("REMOTE").element
    run_until(runtime,
              lambda: remote_stage.remote_topic_path is not None,
              timeout=5.0)
    assert remote_stage.remote_topic_path == child.topic_path

    swag, okay, diagnostic = run_frame(runtime, parent, {"a": 3},
                                       timeout=10.0)
    assert okay, diagnostic
    assert int(swag["REMOTE.x"]) == 6   # doubled remotely
    assert int(swag["x"]) == 7          # then incremented locally


def test_wire_process_frame(runtime):
    """Frames can be injected over the fabric as S-expressions."""
    p = Pipeline(definition(
        ["(A B)"],
        [element("A", "ElementA", ["a"], ["a"]),
         element("B", "ElementB", ["a"], ["b"])],
        name="p_wire"), runtime=runtime)
    got = []
    response_topic = f"{runtime.topic_path_process}/resp"
    runtime.add_message_handler(lambda t, payload: got.append(payload),
                                response_topic)
    runtime.message.publish(
        f"{p.topic_path}/in",
        f"(process_frame (stream_id: 7 response_topic: {response_topic})"
        f" (a: 5))")
    run_until(runtime, lambda: bool(got), timeout=5.0)
    assert got and "process_frame_response" in got[0]
    assert "(b 6)" in got[0] or "b: 6" in got[0]


def test_set_parameter_routing(runtime):
    """(set_parameter ...) wire command: qualified Element.param targets
    the element's own parameters; bare names become pipeline-level
    (reference pipeline.py:1585-1603)."""
    p = Pipeline(definition(
        ["(A)"], [element("A", "ElementA", ["a"], ["a"])]),
        runtime=runtime)
    node = p.graph.get_node("A")

    p.set_parameter("A.gain", 5)
    assert node.element.get_parameter("gain") == (5, True)
    assert p.get_pipeline_parameter("gain") is None   # element-scoped

    p.set_parameter("threshold", 0.5)
    assert node.element.get_parameter("threshold") == (0.5, True)
    assert p.get_pipeline_parameter("threshold") == 0.5

    # Unknown element prefix falls through to a pipeline parameter.
    p.set_parameter("NoSuch.param", 1)
    assert p.get_pipeline_parameter("NoSuch.param") == 1
