"""Mixture-of-experts FFN on the ``ep`` axis (models/llama.py
_moe_ffn + partition_specs; SURVEY §2.5: EP is a first-class axis of
the TPU build -- the reference has no parallelism at all, so this is
the build's own bar).

Covers: parameter/spec structure, exactness of the routed layer against
the dense FFN when routing is trivial (1 expert), ep-sharded vs
unsharded equivalence on the CPU mesh, capacity-drop semantics, the
load-balance aux loss, serving through the continuous batcher, int8
expert weights, and MoE training.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from aiko_services_tpu.models import llama
from aiko_services_tpu.models.quant import quantize_params, quantize_specs
from aiko_services_tpu.parallel import MeshPlan, P

def f32(config):
    return dataclasses.replace(config, dtype="float32")


def test_moe_param_and_spec_structure():
    config = llama.LlamaConfig.tiny_moe()
    params = llama.init_params(jax.random.PRNGKey(0), config)
    layers = params["layers"]
    e, d, f = config.n_experts, config.dim, config.hidden_dim
    assert layers["w_router"].shape == (config.n_layers, d, e)
    assert layers["w_gate"].shape == (config.n_layers, e, d, f)
    assert layers["w_down"].shape == (config.n_layers, e, f, d)
    specs = llama.partition_specs(config)
    # Structure matches: tree_map over (params, specs) must not raise.
    jax.tree_util.tree_map(lambda leaf, s: None, params, specs)
    assert specs["layers"]["w_gate"] == P(None, "ep", "fsdp", "tp")
    assert specs["layers"]["w_router"] == P(None, "fsdp", None)


def test_single_expert_equals_dense_ffn():
    """E=1, k=1 routing is the identity: the MoE block must reproduce
    the dense FFN exactly (gates renormalize to 1, capacity holds every
    token)."""
    dense_config = f32(llama.LlamaConfig.tiny(vocab_size=128,
                                              max_seq=32))
    moe_config = dataclasses.replace(dense_config, n_experts=1,
                                     n_experts_per_token=1)
    dense_params = llama.init_params(jax.random.PRNGKey(0), dense_config)
    moe_params = jax.tree_util.tree_map(lambda x: x, dense_params)
    layers = dict(moe_params["layers"])
    for name in ("w_gate", "w_up", "w_down"):
        layers[name] = layers[name][:, None]        # [L,1,D,F]
    layers["w_router"] = jnp.zeros(
        (moe_config.n_layers, moe_config.dim, 1), dtype=jnp.float32)
    moe_params["layers"] = layers

    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, 128)
    with jax.default_matmul_precision("highest"):
        dense_logits, _ = llama.prefill(
            dense_params, dense_config, tokens,
            llama.init_cache(dense_config, 2, 32),
            jnp.zeros(2, dtype=jnp.int32))
        moe_logits, _ = llama.prefill(
            moe_params, moe_config, tokens,
            llama.init_cache(moe_config, 2, 32),
            jnp.zeros(2, dtype=jnp.int32))
    np.testing.assert_allclose(np.asarray(moe_logits),
                               np.asarray(dense_logits), atol=1e-4)


def test_ep_sharded_matches_unsharded():
    """Expert weights sharded over ep on the 8-device mesh produce the
    same logits as the unsharded forward (XLA derives the expert
    collectives from the partition specs)."""
    config = f32(llama.LlamaConfig.tiny_moe(vocab_size=128, max_seq=32))
    params = llama.init_params(jax.random.PRNGKey(0), config)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 128)

    with jax.default_matmul_precision("highest"):
        ref_logits, _ = llama.prefill(
            params, config, tokens, llama.init_cache(config, 2, 32),
            jnp.zeros(2, dtype=jnp.int32))

        plan = MeshPlan.build({"dp": 2, "ep": 4})
        sharded = plan.put(params, llama.partition_specs(config))
        cache = jax.device_put(
            llama.init_cache(config, 2, 32),
            jax.tree_util.tree_map(plan.shard, llama.cache_specs(config)))
        ep_logits, _ = llama.prefill(
            sharded, config,
            jax.device_put(tokens, plan.shard(P("dp", None))), cache,
            jnp.zeros(2, dtype=jnp.int32))
    np.testing.assert_allclose(np.asarray(ep_logits),
                               np.asarray(ref_logits), atol=1e-4)


def test_capacity_drop_keeps_residual():
    """With a tiny capacity some (token, expert) routes drop; outputs
    stay finite and the dropped tokens keep their residual stream."""
    config = f32(dataclasses.replace(
        llama.LlamaConfig.tiny_moe(vocab_size=128, max_seq=32),
        capacity_factor=0.1))
    params = llama.init_params(jax.random.PRNGKey(0), config)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 128)
    logits, _ = llama.prefill(params, config, tokens,
                              llama.init_cache(config, 2, 32),
                              jnp.zeros(2, dtype=jnp.int32))
    assert bool(jnp.isfinite(logits).all())
    # Capacity respects the config: 0.1 * 32 tokens * 2 / 4 experts
    # -> ceil to the 8-sublane tile.
    assert config.moe_capacity(32) == 8


def test_load_balance_aux():
    """Aux loss is exactly 1.0 under uniform router probabilities and
    approaches E/k as routing collapses onto one expert."""
    config = f32(llama.LlamaConfig.tiny_moe(vocab_size=128, max_seq=64))
    e, d, f = config.n_experts, config.dim, config.hidden_dim
    key = jax.random.PRNGKey(0)
    layer = {
        "w_router": jnp.zeros((d, e), dtype=jnp.float32),
        "w_gate": 0.02 * jax.random.normal(key, (e, d, f)),
        "w_up": 0.02 * jax.random.normal(jax.random.fold_in(key, 1),
                                         (e, d, f)),
        "w_down": 0.02 * jax.random.normal(jax.random.fold_in(key, 2),
                                           (e, f, d)),
    }
    # All-positive activations so a positive router column dominates.
    x = jax.random.uniform(jax.random.fold_in(key, 3), (1, 16, d),
                           minval=0.5, maxval=1.0)
    _, aux_uniform = llama._moe_ffn(config, x, layer)
    assert abs(float(aux_uniform) - 1.0) < 1e-5

    collapsed = dict(layer)
    collapsed["w_router"] = layer["w_router"].at[:, 0].set(10.0)
    _, aux_collapsed = llama._moe_ffn(config, x, collapsed)
    assert float(aux_collapsed) > 1.8      # -> E/k = 2 at full collapse


def test_moe_serving_through_batcher():
    """The continuous batcher serves an MoE config end to end (decode
    routes single tokens; chunked admission routes chunk tokens)."""
    from aiko_services_tpu.models import ContinuousBatcher, Request

    config = llama.LlamaConfig.tiny_moe()
    params = llama.init_params(jax.random.PRNGKey(0), config)
    emitted = {}
    batcher = ContinuousBatcher(params, config, max_slots=2, max_seq=64,
                                prefill_chunk=16, decode_block=4,
                                inflight=2)
    for i in range(3):
        batcher.submit(Request(
            f"r{i}", list(range(1, 8 + i)), max_new_tokens=5,
            emit=lambda r, t, f: emitted.setdefault(r, []).append(t)))
    steps = batcher.run_until_drained(max_steps=300)
    assert steps < 300
    assert sorted(emitted) == ["r0", "r1", "r2"]
    assert all(len(t) == 5 for t in emitted.values())


def test_quantized_moe_forward():
    """Weight-only int8 quantizes the expert-stacked weights too
    (per-output-channel scales broadcast over the capacity axis); on
    grid-aligned weights the forward matches the raw tree."""
    config = f32(llama.LlamaConfig.tiny_moe(vocab_size=256, max_seq=32))
    params = _align_moe(
        llama.init_params(jax.random.PRNGKey(0), config))
    quantized = quantize_params(params)
    assert quantized["layers"]["w_gate"]["int8"].shape \
        == params["layers"]["w_gate"].shape
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, 256)
    raw_logits, _ = llama.prefill(params, config, tokens,
                                  llama.init_cache(config, 2, 32),
                                  jnp.zeros(2, dtype=jnp.int32))
    q_logits, _ = llama.prefill(quantized, config, tokens,
                                llama.init_cache(config, 2, 32),
                                jnp.zeros(2, dtype=jnp.int32))
    np.testing.assert_allclose(np.asarray(raw_logits),
                               np.asarray(q_logits), atol=2e-3)


def _align_moe(params):
    """Grid-align the quantizable weights of an MoE tree (see
    test_quant.grid_aligned_params; that helper builds its own dense
    params, so MoE re-applies the alignment here)."""
    from aiko_services_tpu.models.quant import QUANTIZED_LAYER_KEYS
    key = jax.random.PRNGKey(42)

    def align(weight):
        nonlocal key
        key, sub1, sub2 = jax.random.split(key, 3)
        levels = jax.random.randint(sub1, weight.shape, -127, 128)
        levels = levels.at[..., 0, :].set(127)
        scale = jax.random.uniform(sub2, weight.shape[-1:],
                                   minval=0.5, maxval=2.0) / 127.0
        return (levels * scale).astype(weight.dtype) * 0.05

    layers = dict(params["layers"])
    for name in QUANTIZED_LAYER_KEYS:
        layers[name] = align(layers[name])
    out = dict(params)
    out["layers"] = layers
    out["unembed"] = align(params["unembed"])
    return out


def test_quantized_moe_specs_shard():
    """quantize_specs maps the MoE layout (4-D expert weights) onto the
    quantized structure; the sharded tree decodes on the mesh."""
    config = llama.LlamaConfig.tiny_moe()
    params = quantize_params(
        llama.init_params(jax.random.PRNGKey(0), config))
    specs = quantize_specs(llama.partition_specs(config))
    assert specs["layers"]["w_gate"]["int8"] == P(None, "ep", "fsdp",
                                                  "tp")
    assert specs["layers"]["w_gate"]["scale"] == P(None, "ep", None,
                                                   "tp")
    plan = MeshPlan.build({"dp": 2, "ep": 2, "tp": 2})
    sharded = plan.put(params, specs)
    cache = jax.device_put(
        llama.init_cache(config, 2, 32),
        jax.tree_util.tree_map(plan.shard, llama.cache_specs(config)))
    logits, _ = llama.decode_step(sharded, config,
                                  jnp.zeros(2, dtype=jnp.int32), cache,
                                  jnp.zeros(2, dtype=jnp.int32))
    assert bool(jnp.isfinite(logits.astype(jnp.float32)).all())


def test_moe_train_step_learns():
    """Sharded MoE training on a dp x ep x tp mesh: loss (CE + aux)
    decreases on a repeated batch."""
    from aiko_services_tpu.models.train import (init_train_state,
                                                make_train_step)

    config = llama.LlamaConfig.tiny_moe(vocab_size=128, max_seq=64)
    plan = MeshPlan.build({"dp": 2, "ep": 2, "tp": 2})
    params, opt_state, optimizer = init_train_state(
        jax.random.PRNGKey(0), config, plan)
    step = make_train_step(config, plan, optimizer=optimizer)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (4, 16), 0, 128)
    params, opt_state, loss1 = step(params, opt_state, tokens)
    params, opt_state, loss2 = step(params, opt_state, tokens)
    assert np.isfinite(float(loss1))
    assert float(loss2) < float(loss1)
