"""Multi-path pipeline graphs: one definition, several named entry
paths; a stream runs exactly ONE path, selected by head name
(``Stream.graph_path`` / the wire ``create_stream`` params' graph_path
-- reference pipeline_paths.json + pipeline.py:641)."""

import pathlib
import queue

from conftest import run_until

from aiko_services_tpu.pipeline import create_pipeline

REPO = pathlib.Path(__file__).resolve().parent.parent


def _paths_pipeline(runtime, monkeypatch):
    monkeypatch.chdir(REPO)   # element modules are repo-root relative
    return create_pipeline("examples/pipeline/pipeline_paths.json",
                           runtime=runtime)


def _run_path(pipeline, runtime, graph_path, x):
    responses = queue.Queue()
    stream = pipeline.create_stream_local(graph_path,
                                          graph_path=graph_path,
                                          queue_response=responses)
    assert stream is not None
    pipeline.process_frame_local({"x": x}, stream_id=graph_path)
    assert run_until(runtime, lambda: not responses.empty(), timeout=10.0)
    _, _, swag, _, okay, diagnostic = responses.get()
    assert okay, diagnostic
    return swag


def test_each_path_runs_only_its_elements(runtime, monkeypatch):
    pipeline = _paths_pipeline(runtime, monkeypatch)
    double = _run_path(pipeline, runtime, "in_double", 6)
    square = _run_path(pipeline, runtime, "in_square", 6)
    passthrough = _run_path(pipeline, runtime, "in_pass", 6)

    assert double["result"] == 12
    assert square["result"] == 36
    assert passthrough["result"] == 6
    # Only the selected path's elements executed: the double path never
    # produced a square output and vice versa.
    assert "z" not in double and "y" not in square
    assert "y" not in passthrough and "z" not in passthrough
    pipeline.stop()


def test_wire_create_stream_selects_path(runtime, monkeypatch):
    """The wire command's params dict carries graph_path (reference
    create_stream(graph_path=...))."""
    pipeline = _paths_pipeline(runtime, monkeypatch)
    responses = queue.Queue()
    pipeline.create_stream("wire", {"graph_path": "in_square"})
    stream = pipeline.streams["wire"]
    assert stream.graph_path == "in_square"
    stream.queue_response = responses
    pipeline.process_frame_local({"x": 5}, stream_id="wire")
    assert run_until(runtime, lambda: not responses.empty(), timeout=10.0)
    _, _, swag, _, okay, diagnostic = responses.get()
    assert okay, diagnostic
    assert swag["result"] == 25
    pipeline.stop()


def test_unknown_graph_path_rejected(runtime, monkeypatch):
    pipeline = _paths_pipeline(runtime, monkeypatch)
    assert pipeline.create_stream_local(
        "bad", graph_path="no_such_head") is None
    assert "bad" not in pipeline.streams
    pipeline.stop()


def test_default_path_is_first_head(runtime, monkeypatch):
    pipeline = _paths_pipeline(runtime, monkeypatch)
    # No graph_path: the first declared head's path runs (in_double).
    responses = queue.Queue()
    pipeline.create_stream_local("dflt", queue_response=responses)
    pipeline.process_frame_local({"x": 4}, stream_id="dflt")
    assert run_until(runtime, lambda: not responses.empty(), timeout=10.0)
    _, _, swag, _, okay, diagnostic = responses.get()
    assert okay, diagnostic
    assert swag["result"] == 8
    pipeline.stop()
