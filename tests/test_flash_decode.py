"""Flash-decode (split-K Pallas) kernel: exactness vs the dense decode
path, int8 in-kernel dequantization, and the documented diffuse-attention
error mode of the dense int8 path (ADVICE r3)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from aiko_services_tpu.models import llama
from aiko_services_tpu.models.quant import dequantize_kv, quantize_kv
from aiko_services_tpu.ops.layers import attention_decode_append
from aiko_services_tpu.ops.pallas_decode import flash_decode_append


def _random_case(key, b=3, t=192, k=2, g=2, hd=32, dtype=jnp.float32):
    keys = jax.random.split(key, 5)
    h = k * g
    q = jax.random.normal(keys[0], (b, 1, h, hd), dtype=dtype)
    k_cache = jax.random.normal(keys[1], (b, t, k, hd), dtype=dtype)
    v_cache = jax.random.normal(keys[2], (b, t, k, hd), dtype=dtype)
    k_new = jax.random.normal(keys[3], (b, 1, k, hd), dtype=dtype)
    v_new = jax.random.normal(keys[4], (b, 1, k, hd), dtype=dtype)
    lengths = jnp.asarray([0, 17, t - 33][:b], dtype=jnp.int32)
    return q, k_cache, v_cache, k_new, v_new, lengths


def test_flash_matches_dense_bf16_cache():
    """Raw (unquantized) cache: flash == dense to float tolerance,
    including a zero-length row, a mid-block boundary, and a ragged
    final block (t not a multiple of block_t)."""
    case = _random_case(jax.random.PRNGKey(0))
    q, k_cache, v_cache, k_new, v_new, lengths = case
    dense = attention_decode_append(q, k_cache, v_cache, k_new, v_new,
                                    lengths)
    flash = flash_decode_append(q, k_cache, v_cache, k_new, v_new,
                                lengths, block_t=64)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(dense),
                               atol=1e-5, rtol=1e-5)


def test_flash_int8_matches_dequantized_dense():
    """int8 cache: the kernel's in-kernel dequantization (scales folded
    into scores/weights) is EXACT relative to dequantizing the cache
    first and running the raw dense path -- no query or softmax-weight
    quantization exists on this path."""
    q, k_cache, v_cache, k_new, v_new, lengths = _random_case(
        jax.random.PRNGKey(1))
    k_q = quantize_kv(k_cache)
    v_q = quantize_kv(v_cache)
    reference = attention_decode_append(
        q, dequantize_kv(k_q, jnp.float32), dequantize_kv(v_q, jnp.float32),
        k_new, v_new, lengths)
    flash = flash_decode_append(q, k_q, v_q, k_new, v_new, lengths,
                                block_t=64)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(reference),
                               atol=1e-4, rtol=1e-4)


def _fixed_token_decode(config, steps=4):
    """Run prefill + several fixed-token decode steps; return stacked
    per-step logits."""
    params = llama.init_params(jax.random.PRNGKey(0), config)
    cache = llama.init_cache(config, 2)
    prompt = jnp.asarray([[5, 9, 2, 7], [1, 3, 3, 8]], dtype=jnp.int32)
    logits, cache = llama.prefill(params, config, prompt, cache,
                                  jnp.zeros(2, dtype=jnp.int32))
    lengths = jnp.asarray([4, 4], dtype=jnp.int32)
    outs = [logits[:, -1]]
    for step in range(steps):
        tokens = jnp.asarray([10 + step, 20 + step], dtype=jnp.int32)
        logits, cache = llama.decode_step(params, config, tokens, cache,
                                          lengths)
        lengths = lengths + 1
        outs.append(logits)
    return jnp.stack(outs)


def test_decode_step_flash_matches_dense():
    """decode_step with decode_attention='flash' evolves the same cache
    and produces the same logits as 'dense' over multiple steps."""
    base = llama.LlamaConfig.tiny(vocab_size=64, max_seq=64)
    dense = _fixed_token_decode(
        dataclasses.replace(base, decode_attention="dense"))
    flash = _fixed_token_decode(
        dataclasses.replace(base, decode_attention="flash"))
    np.testing.assert_allclose(np.asarray(flash), np.asarray(dense),
                               atol=5e-2, rtol=2e-2)


def test_decode_step_flash_int8_kv():
    """flash decode_step with an int8 cache stays close to the bf16
    dense path (error bounded by the cache's own storage quantization,
    not by weight truncation)."""
    base = llama.LlamaConfig.tiny(vocab_size=64, max_seq=64)
    dense = _fixed_token_decode(
        dataclasses.replace(base, decode_attention="dense"))
    flash_int8 = _fixed_token_decode(
        dataclasses.replace(base, decode_attention="flash",
                            kv_dtype="int8"))
    np.testing.assert_allclose(np.asarray(flash_int8), np.asarray(dense),
                               atol=0.15, rtol=0.15)


def test_auto_threshold_resolves_at_trace_time():
    """'auto' uses dense below the threshold and flash at/above it --
    both must produce correct results on the same config object.
    max_seq=128: the auto gate also requires a block-aligned extent
    (cache_extent % 128 == 0), so 128 is the smallest extent where the
    flash side actually takes the kernel path."""
    config = llama.LlamaConfig.tiny(
        vocab_size=64, max_seq=128)
    small = dataclasses.replace(config, flash_decode_threshold=32)
    dense_logits = _fixed_token_decode(config)      # 128 < 1024: dense
    flash_logits = _fixed_token_decode(small)       # 128 >= 32: flash
    np.testing.assert_allclose(np.asarray(flash_logits),
                               np.asarray(dense_logits),
                               atol=5e-2, rtol=2e-2)


def test_sharded_cache_never_reaches_flash():
    """ADVICE r4 (medium): pallas_call has no GSPMD partitioning rules,
    so a tp-sharded cache must never reach the flash kernel.  'auto'
    (the default) silently keeps dense for a distributed cache even at
    flash-eligible extents; explicit 'flash' raises eagerly instead of
    compiling a per-layer full-cache all-gather."""
    import pytest

    from aiko_services_tpu.parallel import MeshPlan, make_mesh

    base = dataclasses.replace(
        llama.LlamaConfig.tiny(vocab_size=64, max_seq=128),
        flash_decode_threshold=32)          # 128 is flash-eligible
    params = llama.init_params(jax.random.PRNGKey(0), base)
    plan = MeshPlan(make_mesh({"tp": 2}, jax.devices()[:2]))
    cache = jax.tree_util.tree_map(
        lambda a, s: jax.device_put(a, plan.shard(*s)),
        llama.init_cache(base, 2), llama.cache_specs(base))
    tokens = jnp.asarray([3, 5], dtype=jnp.int32)
    lengths = jnp.asarray([4, 4], dtype=jnp.int32)

    flash = dataclasses.replace(base, decode_attention="flash")
    with pytest.raises(ValueError, match="resident"):
        llama.decode_step(params, flash, tokens, cache, lengths)

    auto = dataclasses.replace(base, decode_attention="auto")
    logits, _ = llama.decode_step(params, auto, tokens, cache, lengths)
    assert bool(jnp.isfinite(logits.astype(jnp.float32)).all())

    # The same extent with a RESIDENT cache still picks flash (the gate
    # only bites when the cache is actually distributed).
    resident = llama.init_cache(base, 2)
    from aiko_services_tpu.models.llama import _resolve_decode_flash
    assert _resolve_decode_flash(auto, resident) is True
    assert _resolve_decode_flash(auto, cache) is False


def test_mixed_quantization_cache_rejected():
    """ADVICE r4: the kernel keys its in-kernel dequant on the k scales
    alone; a half-quantized k/v pair is caller error and must raise, not
    silently misread v."""
    import pytest

    q, k_cache, v_cache, k_new, v_new, lengths = _random_case(
        jax.random.PRNGKey(2))
    with pytest.raises(ValueError, match="quantization state"):
        flash_decode_append(q, quantize_kv(k_cache), v_cache, k_new,
                            v_new, lengths)
    with pytest.raises(ValueError, match="quantization state"):
        flash_decode_append(q, k_cache, quantize_kv(v_cache), k_new,
                            v_new, lengths)


def test_dense_int8_diffuse_tail_error_mode():
    """ADVICE r3 (medium): the DENSE int8 path quantizes softmax weights
    per (b, h) with step = row_max / 127; a distribution with one spike
    and a diffuse tail (every tail weight below half the step) drops
    most of the attention mass from the numerator.  This test quantifies
    that worst case at T=8k -- and shows the flash path, which never
    quantizes weights, stays exact on the same input.  See the
    attention_decode_append docstring for the documented bound."""
    b, t, k, hd = 1, 8192, 1, 16
    # q aligned with the first k component: logits = cache[:, 0] / sqrt(hd)
    q = jnp.zeros((b, 1, 1, hd)).at[..., 0].set(hd ** 0.5)
    # One spike at position 0, a uniform tail whose exact softmax weight
    # is ~1/260 of the spike's: below half the int8 step (1/254).
    tail_logit = -np.log(260.0)
    k_vals = jnp.full((b, t, k, hd), 0.0).at[..., 0].set(tail_logit)
    k_vals = k_vals.at[:, 0, :, 0].set(0.0)
    v_vals = jnp.ones((b, t, k, hd))       # every position contributes 1
    k_new = jnp.full((b, 1, k, hd), -1e3)  # self term negligible
    v_new = jnp.zeros((b, 1, k, hd))
    lengths = jnp.asarray([t], dtype=jnp.int32)

    exact = attention_decode_append(q, k_vals, v_vals, k_new, v_new,
                                    lengths)
    # int8 cache whose stored values round-trip exactly (amax scales on
    # these constants introduce ~0.4% -- negligible next to the mode
    # under test).
    k_q, v_q = quantize_kv(k_vals), quantize_kv(v_vals)
    dense_int8 = attention_decode_append(q, k_q, v_q, k_new, v_new,
                                         lengths)
    flash_int8 = flash_decode_append(q, k_q, v_q, k_new, v_new, lengths)

    # All weights hit v=1, so the exact output is ~1.  The dense int8
    # path keeps only the spike's share of the numerator (~1/32 here:
    # spike 1 vs tail mass 8191/260) while the denominator stays exact:
    # output shrinks toward spike/total -- the documented shrink-only
    # failure.  The flash path stays at the exact value.
    exact_val = float(np.asarray(exact)[0, 0, 0, 0])
    dense_val = float(np.asarray(dense_int8)[0, 0, 0, 0])
    flash_val = float(np.asarray(flash_int8)[0, 0, 0, 0])
    assert abs(exact_val - 1.0) < 1e-3
    assert dense_val < 0.2 * exact_val      # the documented worst case
    assert abs(flash_val - exact_val) < 5e-3
