"""Stage re-placement on device failure (SURVEY §5.3 TPU-equiv: chip
health checks + re-shard onto surviving chips), on the 8-device CPU
mesh."""

import queue

import jax
import numpy as np
import pytest

from conftest import run_until
from aiko_services_tpu.pipeline import Pipeline
from aiko_services_tpu.pipeline.tensor import StagePlacement, TPUElement
from aiko_services_tpu.pipeline.stream import StreamEvent
from aiko_services_tpu.tpu.health import probe_devices


def test_probe_devices_default_prober_all_healthy():
    assert probe_devices(jax.devices()) == []


def test_probe_devices_injected_failure():
    devices = jax.devices()
    dead = {devices[3], devices[5]}
    failed = probe_devices(devices, prober=lambda d: d not in dead)
    assert set(failed) == dead


def test_replace_rebuilds_plans_on_survivors():
    placement = StagePlacement(jax.devices())
    placement.assign({"detect": {"dp": 4}, "llm": {"tp": 4}})
    detect_devices = list(placement.plans["detect"].mesh.devices.flat)

    failed = detect_devices[:2]              # two chips of stage 1 die
    placement.replace(failed)

    assert placement.generation == 1
    survivors = set(jax.devices()) - set(failed)
    placed = [d for plan in placement.plans.values()
              for d in plan.mesh.devices.flat]
    assert set(placed) <= survivors
    # 6 survivors for requests (4 + 4): largest stage halved once.
    shapes = {name: dict(plan.mesh.shape)
              for name, plan in placement.plans.items()}
    assert sorted(int(np.prod(list(s.values())))
                  for s in shapes.values()) == [2, 4]
    # Data still lands on the new meshes.
    array = placement.transfer(np.ones((4, 4), np.float32), "llm")
    assert jax.block_until_ready(array).sum() == 16


def test_replace_all_dead_raises():
    placement = StagePlacement(jax.devices())
    placement.assign({"s": {"dp": 8}})
    with pytest.raises(RuntimeError, match="no surviving"):
        placement.replace(list(jax.devices()))


def test_replace_cannot_shrink_below_one_device():
    devices = jax.devices()[:2]
    placement = StagePlacement(devices)
    placement.assign({"a": {"dp": 1}, "b": {"dp": 1}})
    with pytest.raises(RuntimeError, match="cannot shrink"):
        placement.replace([devices[0]])


class PlacedSquare(TPUElement):
    """Jitted square on its placed submesh; counts re-placements."""

    replaced = 0

    def process_frame(self, stream, x):
        compute = self.jit(lambda a: a * a)
        value = self.put(np.asarray(x, np.float32))
        return StreamEvent.OKAY, {"y": compute(value)}

    def on_replacement(self):
        super().on_replacement()
        PlacedSquare.replaced += 1


def element_def(name, cls, inputs, outputs, placement):
    return {"name": name,
            "input": [{"name": n} for n in inputs],
            "output": [{"name": n} for n in outputs],
            "deploy": {"local": {"module": "tests/test_replacement.py",
                                 "class_name": cls}},
            "parameters": {}, "placement": placement}


def run_frame(runtime, pipeline, frame_data):
    responses = queue.Queue()
    pipeline.process_frame_local(frame_data, queue_response=responses)
    assert run_until(runtime, lambda: not responses.empty(), timeout=10.0)
    _, _, swag, _, okay, diagnostic = responses.get()
    assert okay, diagnostic
    return swag


def test_pipeline_replaces_stage_and_keeps_processing(runtime):
    """End to end: a placed pipeline loses two chips mid-stream; health
    check re-places the stage, the element recompiles on the smaller
    submesh, and frames keep flowing."""
    PlacedSquare.replaced = 0
    pipeline = Pipeline(
        {"version": 0, "name": "p_replace", "runtime": "jax",
         "graph": ["(Sq)"], "parameters": {},
         "elements": [element_def("Sq", "PlacedSquare", ["x"], ["y"],
                                  {"mesh": {"dp": 4}})]},
        runtime=runtime)
    swag = run_frame(runtime, pipeline, {"x": 3.0})
    assert float(swag["y"]) == 9.0
    placement = pipeline.stage_placement
    old_devices = list(placement.plans["Sq"].mesh.devices.flat)
    assert len(old_devices) == 4

    # The element class is re-imported by module path: reach the live
    # instance through the graph, not the pytest import of this file.
    sq_element = next(node.element for node in pipeline.graph.nodes()
                      if node.name == "Sq")
    events = []
    pipeline.add_hook_handler(
        "pipeline.replacement:0",
        lambda component, hook, variables: events.append(variables))
    dead = set(old_devices[:2])
    failed = pipeline.check_device_health(
        prober=lambda d: d not in dead)
    assert set(failed) == dead
    assert type(sq_element).replaced == 1
    assert pipeline.share["replacements"] == 1
    assert len(events) == 1
    assert events[0]["generation"] == 1
    # 6 healthy chips remain for a 4-chip request: spare capacity
    # absorbs the failure, the stage keeps its full mesh -- on fresh
    # devices.
    assert events[0]["stages"] == {"Sq": {"dp": 4}}

    new_devices = list(placement.plans["Sq"].mesh.devices.flat)
    assert not (set(new_devices) & dead)
    assert len(new_devices) == 4

    swag = run_frame(runtime, pipeline, {"x": 5.0})
    assert float(swag["y"]) == 25.0

    # Healthy probe is a no-op.
    assert pipeline.check_device_health(prober=lambda d: True) == []
    assert type(sq_element).replaced == 1


def test_probe_hung_prober_counts_as_failed():
    """A hung chip must not freeze the caller: the probe deadline expires
    and the device is reported failed."""
    import threading
    import time

    devices = jax.devices()[:3]
    hang_forever = threading.Event()

    def prober(device):
        if device is devices[1]:
            hang_forever.wait(timeout=30.0)     # "hung transfer"
        return True

    start = time.perf_counter()
    failed = probe_devices(devices, prober=prober, timeout=0.3)
    elapsed = time.perf_counter() - start
    hang_forever.set()
    assert failed == [devices[1]]
    assert elapsed < 5.0


def test_unrecoverable_failure_is_terminal(runtime):
    """Too few survivors: the health timer stops, placement_failed is
    shared, and live streams error instead of retrying forever."""
    devices = jax.devices()
    pipeline = Pipeline(
        {"version": 0, "name": "p_term", "runtime": "jax",
         "graph": ["(A B)"],
         "parameters": {"health_check_interval": 0.05},
         "elements": [
             element_def("A", "PlacedSquare", ["x"], ["y"],
                         {"mesh": {"dp": 4}}),
             element_def("B", "PlacedSquare", ["y"], ["z"],
                         {"mesh": {"dp": 4}})]},
        runtime=runtime)
    assert pipeline._health_timer is not None
    responses = queue.Queue()
    stream = pipeline.create_stream_local("s1", queue_response=responses)
    assert stream is not None

    # 7 of 8 die: even fully shrunk, two stages need 2 chips and only
    # 1 survives -> unrecoverable.
    dead = set(devices[:7])
    failed = pipeline.check_device_health(prober=lambda d: d not in dead)
    assert len(failed) == 7
    assert "placement_failed" in pipeline.share
    assert pipeline._health_timer is None        # retry loop stopped
    assert "s1" not in pipeline.streams          # stream torn down
