"""Overlapped frame execution (ISSUE 1): device-resident swag between
consecutive device elements, the transfer-guard ledger, the bounded
per-stream dispatch window, and cross-stream micro-batching.

The transfer-guard contract is enforced two ways: the real
``jax.transfer_guard`` wraps device elements (effective on TPU, where a
device->host copy is a transfer), and a software residency check
catches declared-``tensor`` outputs arriving host-side -- which is what
fires on this CPU backend, where d2h is zero-copy and the jax guard
never trips.  These tests run small pipelines under
``transfer_guard: disallow`` so a host-sync regression on the
device-element path fails fast here in tier-1, not on hardware.
"""

import json
import queue
import threading
import time

import numpy as np

import jax
import jax.numpy as jnp

from conftest import run_until

from aiko_services_tpu.pipeline import (PipelineElement, StreamEvent,
                                        create_pipeline)
from aiko_services_tpu.pipeline.codec import (decode_frame_data,
                                              decode_value,
                                              encode_frame_data,
                                              encode_value)

DELAY = 0.05

# (element name, arrived-as-jax.Array) per process_frame call, so tests
# can assert values stayed device-resident BETWEEN elements.
ARRIVALS: list = []


class DeviceUpload(PipelineElement):
    """Head element: host value -> device array (one explicit upload)."""

    device_resident = True

    def process_frame(self, stream, x=None, **inputs):
        return StreamEvent.OKAY, {"x": jnp.asarray(x)}


class DeviceDouble(PipelineElement):
    """Device stage: consumes and produces jax.Array, never syncing."""

    device_resident = True

    def process_frame(self, stream, x=None, **inputs):
        ARRIVALS.append((self.name, isinstance(x, jax.Array)))
        return StreamEvent.OKAY, {"x": jnp.asarray(x) * 2}


class HostSink(PipelineElement):
    """Host stage (``host_inputs``): the engine fetches explicitly."""

    host_inputs = ("x",)

    def process_frame(self, stream, x=None, **inputs):
        ARRIVALS.append((self.name, isinstance(x, jax.Array)))
        return StreamEvent.OKAY, {"total": float(np.asarray(x).sum())}


class LeakyDevice(PipelineElement):
    """Regression stand-in: a device element that fetches its declared
    device output to host (the np.asarray-per-row class of bug)."""

    device_resident = True

    def process_frame(self, stream, x=None, **inputs):
        return StreamEvent.OKAY, {"x": np.asarray(jnp.asarray(x) * 2)}


class AsyncDevice(PipelineElement):
    """Async device stage with a fixed service delay: dispatches device
    work immediately (the output array is handed over un-synced) and
    completes ``delay`` seconds later -- an accelerator stage."""

    device_resident = True
    is_async = True

    def process_frame_start(self, stream, complete, x=None, **inputs):
        y = jnp.asarray(x) + 1
        delay, _ = self.get_parameter("delay", DELAY)
        threading.Timer(float(delay),
                        lambda: complete(StreamEvent.OKAY, {"x": y})).start()


def _definition(tmp_path, elements, graph, parameters=None,
                types=None):
    """elements: [(name, class_name, element_params)]; all single
    input/output ``x`` unless ``types`` overrides the output type."""
    body = {
        "version": 0, "name": "overlap", "runtime": "jax",
        "graph": graph, "parameters": parameters or {},
        "elements": [
            {"name": name,
             "input": [{"name": "x"}],
             "output": [{"name": "x",
                         "type": (types or {}).get(name, "any")}],
             "parameters": params or {},
             "deploy": {"local": {"module": "test_overlap",
                                  "class_name": cls}}}
            for name, cls, params in elements]}
    path = tmp_path / "overlap.json"
    path.write_text(json.dumps(body))
    return str(path)


def _pump(pipeline, stream, values):
    for value in values:
        pipeline.create_frame_local(stream, {"x": value})


# -- device-resident swag between consecutive device elements -----------

def test_swag_stays_device_resident_between_device_elements(
        tmp_path, runtime):
    ARRIVALS.clear()
    responses = queue.Queue()
    pipeline = create_pipeline(
        _definition(tmp_path,
                    [("up", "DeviceUpload", {}),
                     ("d1", "DeviceDouble", {}),
                     ("d2", "DeviceDouble", {})],
                    ["(up d1 d2)"],
                    parameters={"transfer_guard": "disallow"}),
        runtime=runtime)
    stream = pipeline.create_stream_local("s", queue_response=responses)
    _pump(pipeline, stream, [np.arange(4, dtype=np.float32)])
    assert run_until(runtime, lambda: not responses.empty(), timeout=10.0)
    _, _, swag, _, okay, diagnostic = responses.get()
    assert okay, diagnostic
    # Both device stages saw a jax.Array -- no host round trip between
    # consecutive device elements...
    assert ARRIVALS == [("d1", True), ("d2", True)]
    # ...and the local response still passes by reference, device-side.
    assert isinstance(swag["x"], jax.Array)
    np.testing.assert_allclose(np.asarray(swag["x"]),
                               np.arange(4, dtype=np.float32) * 4)
    # Transfer-guard counter == 0: nothing implicit, nothing fetched.
    stats = pipeline.transfer_stats()
    assert stats["implicit"] == 0
    assert stats["explicit"] == 0
    pipeline.stop()


def test_host_typed_input_is_fetched_explicitly(tmp_path, runtime):
    ARRIVALS.clear()
    responses = queue.Queue()
    pipeline = create_pipeline(
        _definition(tmp_path,
                    [("up", "DeviceUpload", {}),
                     ("sink", "HostSink", {})],
                    ["(up sink)"],
                    parameters={"transfer_guard": "disallow"}),
        runtime=runtime)
    stream = pipeline.create_stream_local("s", queue_response=responses)
    _pump(pipeline, stream, [np.arange(4, dtype=np.float32)])
    assert run_until(runtime, lambda: not responses.empty(), timeout=10.0)
    _, _, swag, _, okay, diagnostic = responses.get()
    assert okay, diagnostic
    assert swag["total"] == 6.0
    assert ARRIVALS == [("sink", False)]    # materialized host-side
    stats = pipeline.transfer_stats()
    assert stats["explicit"] == 1           # ONE counted engine fetch
    assert stats["implicit"] == 0
    pipeline.stop()


def test_transfer_guard_disallow_fails_host_sync_fast(tmp_path, runtime):
    """The tier-1 regression tripwire: a device element whose declared
    device output comes back host-resident must FAIL the frame under
    ``transfer_guard: disallow`` (and count), not silently halve fps."""
    responses = queue.Queue()
    pipeline = create_pipeline(
        _definition(tmp_path,
                    [("up", "DeviceUpload", {}),
                     ("leak", "LeakyDevice", {})],
                    ["(up leak)"],
                    parameters={"transfer_guard": "disallow"},
                    types={"leak": "tensor"}),
        runtime=runtime)
    stream = pipeline.create_stream_local("s", queue_response=responses)
    _pump(pipeline, stream, [np.arange(4, dtype=np.float32)])
    assert run_until(runtime, lambda: not responses.empty(), timeout=10.0)
    _, _, _, _, okay, diagnostic = responses.get()
    assert not okay
    assert "transfer_guard" in diagnostic
    assert pipeline.transfer_stats()["implicit"] == 1
    pipeline.stop()


def test_transfer_guard_log_records_without_failing(tmp_path, runtime):
    responses = queue.Queue()
    pipeline = create_pipeline(
        _definition(tmp_path,
                    [("up", "DeviceUpload", {}),
                     ("leak", "LeakyDevice", {})],
                    ["(up leak)"],
                    parameters={"transfer_guard": "log"},
                    types={"leak": "tensor"}),
        runtime=runtime)
    stream = pipeline.create_stream_local("s", queue_response=responses)
    _pump(pipeline, stream, [np.arange(4, dtype=np.float32)])
    assert run_until(runtime, lambda: not responses.empty(), timeout=10.0)
    *_, okay, diagnostic = responses.get()
    assert okay, diagnostic                 # recorded, not failed
    assert pipeline.transfer_stats()["implicit"] == 1
    pipeline.stop()


# -- the overlap window (two streams, two device elements) ---------------

def test_frames_overlap_across_device_stages_two_streams(
        tmp_path, runtime):
    """Satellite: a two-stream, two-device-element pipeline where (a)
    nothing transfers implicitly (counter == 0) and (b) frame k+1's
    first element STARTS before frame k's last element COMPLETES --
    proven from the engine's absolute per-element start stamps."""
    frames_per_stream = 3
    definition = _definition(
        tmp_path,
        [("a", "AsyncDevice", {}), ("b", "AsyncDevice", {})],
        ["(a b)"],
        parameters={"transfer_guard": "disallow"})
    pipeline = create_pipeline(definition, runtime=runtime)
    collected: dict = {"s1": [], "s2": []}
    queues = {}
    for stream_id in collected:
        queues[stream_id] = queue.Queue()
        stream = pipeline.create_stream_local(
            stream_id, queue_response=queues[stream_id])
        _pump(pipeline, stream,
              [np.full((8,), i, dtype=np.float32)
               for i in range(frames_per_stream)])
    assert run_until(
        runtime,
        lambda: all(queues[s].qsize() >= frames_per_stream
                    for s in queues),
        timeout=30.0)
    for stream_id, rows in collected.items():
        while not queues[stream_id].empty():
            _, frame_id, swag, metrics, okay, diagnostic = \
                queues[stream_id].get()
            assert okay, diagnostic
            assert isinstance(swag["x"], jax.Array)  # stayed device
            rows.append((frame_id, metrics))
        rows.sort()
        assert len(rows) == frames_per_stream
        for (_, earlier), (_, later) in zip(rows, rows[1:]):
            k_last_done = earlier["b_time_start"] + earlier["b_time"]
            assert later["a_time_start"] < k_last_done, (
                f"stream {stream_id}: frame k+1's first element "
                f"started {later['a_time_start'] - k_last_done:.3f}s "
                f"AFTER frame k's last element completed -- no overlap")
    stats = pipeline.transfer_stats()
    assert stats["implicit"] == 0           # (a) nothing transferred
    pipeline.stop()


# -- bounded dispatch window --------------------------------------------

def test_device_window_bounds_inflight_dispatch(tmp_path, runtime):
    frames = 6
    limit = 2
    responses = queue.Queue()
    pipeline = create_pipeline(
        _definition(tmp_path,
                    [("up", "DeviceUpload", {}),
                     ("d1", "DeviceDouble", {})],
                    ["(up d1)"],
                    parameters={"device_inflight": limit}),
        runtime=runtime)
    stream = pipeline.create_stream_local("s", queue_response=responses)
    _pump(pipeline, stream,
          [np.arange(4, dtype=np.float32)] * frames)
    assert run_until(runtime, lambda: responses.qsize() >= frames,
                     timeout=15.0)
    window = stream.device_window
    # Every completed frame carried device leaves into the window; the
    # pacing kept at most `limit` outstanding and synced the rest.
    assert window.noted == frames
    assert window.outstanding <= limit
    assert window.synced >= frames - limit
    pipeline.stop()


def test_device_window_disabled_never_paces(tmp_path, runtime):
    responses = queue.Queue()
    pipeline = create_pipeline(
        _definition(tmp_path,
                    [("up", "DeviceUpload", {}),
                     ("d1", "DeviceDouble", {})],
                    ["(up d1)"],
                    parameters={"device_inflight": 0}),
        runtime=runtime)
    stream = pipeline.create_stream_local("s", queue_response=responses)
    _pump(pipeline, stream, [np.arange(4, dtype=np.float32)] * 4)
    assert run_until(runtime, lambda: responses.qsize() >= 4,
                     timeout=15.0)
    assert stream.device_window.synced == 0
    pipeline.stop()


# -- cross-stream micro-batching (MicroBatcher elements) -----------------

def _media_definition(tmp_path, name, cls, module, inputs, outputs,
                      params):
    body = {
        "version": 0, "name": f"mb_{name}", "runtime": "jax",
        "graph": [f"({name})"], "parameters": {},
        "elements": [{
            "name": name,
            "input": [{"name": n} for n in inputs],
            "output": [{"name": n} for n in outputs],
            "parameters": params,
            "deploy": {"local": {"module": module, "class_name": cls}}}]}
    path = tmp_path / f"{name}.json"
    path.write_text(json.dumps(body))
    return str(path)


def test_resize_microbatches_across_streams(tmp_path, runtime):
    """Frames parked at ImageResize from TWO streams resize as ONE
    batched dispatch, each getting its own row -- identical to the
    blocking path -- and the rows stay device-resident."""
    definition = _media_definition(
        tmp_path, "resize", "ImageResize",
        "aiko_services_tpu.elements.image", ["image"], ["image"],
        {"width": 16, "height": 16})
    pipeline = create_pipeline(definition, runtime=runtime)
    rng = np.random.default_rng(0)
    images = {f"s{i}": rng.integers(0, 255, (32, 32, 3)).astype(np.uint8)
              for i in range(2)}
    queues = {}
    for stream_id, image in images.items():
        queues[stream_id] = queue.Queue()
        stream = pipeline.create_stream_local(
            stream_id, queue_response=queues[stream_id])
        pipeline.create_frame_local(stream, {"image": image})
    assert run_until(runtime,
                     lambda: all(not q.empty() for q in queues.values()),
                     timeout=30.0)
    element = pipeline.graph.get_node("resize").element
    assert element._batcher.dispatches == 1, (
        f"{element._batcher.dispatches} dispatches for 2 frames: "
        f"not cross-stream batched")
    for stream_id, image in images.items():
        _, _, swag, _, okay, diagnostic = queues[stream_id].get()
        assert okay, diagnostic
        resized = swag["image"]
        assert isinstance(resized, jax.Array)       # device-resident
        assert resized.shape == (16, 16, 3)
        _, sync_out = element.process_frame(None, image=image)
        np.testing.assert_array_equal(np.asarray(resized),
                                      np.asarray(sync_out["image"]))
    pipeline.stop()


def test_audio_fft_microbatch_matches_sync(tmp_path, runtime):
    definition = _media_definition(
        tmp_path, "fft", "AudioFFT",
        "aiko_services_tpu.elements.audio",
        ["frames", "sample_rate"], ["spectrum", "sample_rate"], {})
    pipeline = create_pipeline(definition, runtime=runtime)
    rng = np.random.default_rng(1)
    responses = queue.Queue()
    stream = pipeline.create_stream_local("s", queue_response=responses)
    windows = [rng.standard_normal((4, 64, 1)).astype(np.float32)
               for _ in range(3)]
    for w in windows:
        pipeline.create_frame_local(
            stream, {"frames": w, "sample_rate": 16000})
    assert run_until(runtime, lambda: responses.qsize() >= 3,
                     timeout=30.0)
    element = pipeline.graph.get_node("fft").element
    assert element._batcher.dispatches < 3      # coalesced
    by_frame = {}
    while not responses.empty():
        _, frame_id, swag, _, okay, diagnostic = responses.get()
        assert okay, diagnostic
        by_frame[frame_id] = swag
    for frame_id, w in enumerate(windows):
        _, sync_out = element.process_frame(None, frames=w)
        np.testing.assert_allclose(
            np.asarray(by_frame[frame_id]["spectrum"]),
            np.asarray(sync_out["spectrum"]), rtol=1e-5, atol=1e-5)
    pipeline.stop()


def test_audio_fft_accepts_array_like_frames(tmp_path, runtime):
    """Plain nested lists -- anything ``jnp.asarray`` accepts -- must
    still work through the async micro-batched path (the sync path
    always took them)."""
    definition = _media_definition(
        tmp_path, "fft", "AudioFFT",
        "aiko_services_tpu.elements.audio",
        ["frames", "sample_rate"], ["spectrum", "sample_rate"], {})
    pipeline = create_pipeline(definition, runtime=runtime)
    responses = queue.Queue()
    stream = pipeline.create_stream_local("s", queue_response=responses)
    frames = [[0.0, 1.0, 0.0, -1.0]] * 2        # [2 windows, 4 samples]
    pipeline.create_frame_local(
        stream, {"frames": frames, "sample_rate": 16000})
    assert run_until(runtime, lambda: not responses.empty(), timeout=30.0)
    _, _, swag, _, okay, diagnostic = responses.get()
    assert okay, diagnostic
    element = pipeline.graph.get_node("fft").element
    _, sync_out = element.process_frame(None, frames=frames)
    np.testing.assert_allclose(np.asarray(swag["spectrum"]),
                               np.asarray(sync_out["spectrum"]),
                               rtol=1e-5, atol=1e-5)
    pipeline.stop()


def test_detector_mixed_dtype_burst_normalizes_each_group(
        tmp_path, runtime):
    """A uint8 frame and a float32 frame of the same shape submitted in
    one burst must EACH match their own blocking-path output: raw-dtype
    grouping keeps the /255 normalization per group (regression: a
    shared key let the stacked batch promote to float32 and the uint8
    rows skipped normalization)."""
    definition = _media_definition(
        tmp_path, "detect", "Detector",
        "aiko_services_tpu.elements.detect", ["image"], ["detections"],
        {"width": 4})
    pipeline = create_pipeline(definition, runtime=runtime)
    rng = np.random.default_rng(0)
    u8 = rng.integers(0, 255, (64, 64, 3)).astype(np.uint8)
    f32 = rng.random((64, 64, 3)).astype(np.float32)
    responses = queue.Queue()
    stream = pipeline.create_stream_local("s", queue_response=responses)
    pipeline.create_frame_local(stream, {"image": u8})
    pipeline.create_frame_local(stream, {"image": f32})
    assert run_until(runtime, lambda: responses.qsize() >= 2,
                     timeout=60.0)
    element = pipeline.graph.get_node("detect").element
    by_frame = {}
    while not responses.empty():
        _, frame_id, swag, _, okay, diagnostic = responses.get()
        assert okay, diagnostic
        by_frame[frame_id] = swag["detections"]
    for frame_id, image in enumerate((u8, f32)):
        _, sync_out = element.process_frame(stream, image=image)
        assert by_frame[frame_id] == sync_out["detections"]
    pipeline.stop()


# -- codec round trips (process-boundary satellite) ----------------------

def test_codec_roundtrip_zero_dim_scalars():
    for value in (jnp.float32(3.5), jnp.int32(-7),
                  np.float64(2.25), jnp.bfloat16(1.5)):
        decoded = decode_value(encode_value(value))
        assert decoded.shape == ()
        assert decoded.dtype == np.asarray(value).dtype
        np.testing.assert_allclose(np.asarray(decoded, dtype=np.float64),
                                   float(value))


def test_codec_roundtrip_bf16_arrays():
    array = jnp.arange(6, dtype=jnp.bfloat16).reshape(2, 3) / 4
    decoded = decode_value(encode_value(array))
    assert decoded.dtype == np.asarray(array).dtype    # bfloat16 kept
    assert decoded.shape == (2, 3)
    np.testing.assert_array_equal(decoded, np.asarray(array))


def test_codec_plain_void_dtype_falls_back_to_npy():
    """Unstructured void dtypes that are NOT ml_dtypes extensions keep
    the plain npy path (lossy dtype but no crash), as before."""
    value = np.zeros(4, dtype="V3")
    encoded = encode_value(value)
    assert isinstance(encoded, str) and encoded.startswith("npy64:")


def test_codec_roundtrip_nested_frame_data():
    frame = {"image": np.zeros((2, 2, 3), dtype=np.uint8),
             "logits": jnp.ones((4,), dtype=jnp.bfloat16),
             "meta": {"names": ["a", "b"], "score": 0.5},
             "rows": [jnp.float32(1.0), "text"]}
    decoded = decode_frame_data(encode_frame_data(frame))
    assert decoded["meta"] == {"names": ["a", "b"], "score": 0.5}
    assert decoded["rows"][1] == "text"
    assert decoded["logits"].dtype == np.asarray(frame["logits"]).dtype
    np.testing.assert_array_equal(decoded["image"], frame["image"])
    np.testing.assert_array_equal(decoded["rows"][0],
                                  np.asarray(frame["rows"][0]))
