"""Kernel plane (ISSUE 11): paged flash-decode, batched chunk-verify,
fused int8 dequant-matmul and on-TPU top-k -- every kernel exercised
under ``interpret=True`` on the CPU mesh, so the equivalence tests gate
PRs without TPU hardware (the ``kernel-test`` selfcheck rule enforces
the kernel <-> test pairing repo-wide)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiko_services_tpu.models import llama
from aiko_services_tpu.models.paged import init_paged_cache
from aiko_services_tpu.models.quant import (dequantize_kv, quantize_kv,
                                            quantize_params,
                                            quantize_weight)
from aiko_services_tpu.ops import decode_backend, matmul_backend, topk
from aiko_services_tpu.ops.layers import (attention_decode_append,
                                          attention_prefill)
from aiko_services_tpu.ops.pallas_decode import (
    _prep_query, _split_paged, flash_decode_append_paged,
    flash_decode_attention, flash_decode_attention_paged,
    flash_verify_append)
from aiko_services_tpu.ops.pallas_matmul import int8_matmul
from aiko_services_tpu.ops.pallas_topk import topk as pallas_topk


# -- paged flash-decode -----------------------------------------------------

def _paged_case(key, dtype=jnp.float32, quantized=False):
    """A small paged pool + table whose gathered view is the dense
    reference: L=2 layers, 3 slots x 4 logical pages of 32 tokens."""
    L, P, pt, B, K, G, hd = 2, 13, 32, 3, 2, 2, 16
    C = K * hd
    table = jnp.asarray([[1, 2, 3, 0], [4, 5, 6, 7], [8, 9, 0, 0]],
                        dtype=jnp.int32)
    lengths = jnp.asarray([70, 128, 33], dtype=jnp.int32)
    raw_k = jax.random.normal(key, (L, P, pt, K, hd), dtype=jnp.float32)
    raw_v = jax.random.normal(jax.random.fold_in(key, 1),
                              (L, P, pt, K, hd), dtype=jnp.float32)
    if quantized:
        qk, qv = quantize_kv(raw_k), quantize_kv(raw_v)
        pool_k = {"int8": qk["int8"].reshape(L, P, pt, C),
                  "scale": qk["scale"]}
        pool_v = {"int8": qv["int8"].reshape(L, P, pt, C),
                  "scale": qv["scale"]}
        dense_k = dequantize_kv(qk, jnp.float32)
        dense_v = dequantize_kv(qv, jnp.float32)
    else:
        pool_k = raw_k.reshape(L, P, pt, C).astype(dtype)
        pool_v = raw_v.reshape(L, P, pt, C).astype(dtype)
        dense_k = pool_k.reshape(L, P, pt, K, hd)
        dense_v = pool_v.reshape(L, P, pt, K, hd)
    q = jax.random.normal(jax.random.fold_in(key, 2), (B, 1, K * G, hd),
                          dtype=dtype)
    k_new = jax.random.normal(jax.random.fold_in(key, 3), (B, 1, K, hd),
                              dtype=dtype)
    v_new = jax.random.normal(jax.random.fold_in(key, 4), (B, 1, K, hd),
                              dtype=dtype)
    return (pool_k, pool_v, dense_k, dense_v, table, lengths, q, k_new,
            v_new, dict(L=L, P=P, pt=pt, B=B, K=K, G=G, hd=hd, C=C))


def test_paged_kernel_bitwise_matches_dense_kernel():
    """f32 acceptance gate: the paged kernel walking the page table
    in-kernel is BITWISE identical to the dense split-K kernel run on
    the gathered contiguous view (same block size -> same op sequence),
    on every layer -- the strongest possible no-gather equivalence."""
    (pool_k, pool_v, dense_k, dense_v, table, lengths, q, _, _,
     dims) = _paged_case(jax.random.PRNGKey(0))
    B, pt, K, hd, C = (dims["B"], dims["pt"], dims["K"], dims["hd"],
                       dims["C"])
    h = q.shape[2]
    q_pad, _, _, _ = _prep_query(q[:, 0], h, K, hd)
    for layer in range(dims["L"]):
        gathered = pool_k[layer][table].reshape(B, -1, C)
        gathered_v = pool_v[layer][table].reshape(B, -1, C)
        acc_d, m_d, l_d = flash_decode_attention(
            q_pad, gathered, gathered_v, None, None, lengths,
            block_t=pt, interpret=True)
        acc_p, m_p, l_p = flash_decode_attention_paged(
            q_pad, pool_k, pool_v, None, None, jnp.int32(layer), table,
            lengths, interpret=True)
        for dense, paged in ((acc_d, acc_p), (m_d, m_p), (l_d, l_p)):
            assert np.array_equal(np.asarray(dense), np.asarray(paged))


def test_paged_append_matches_dense_reference_f32():
    (pool_k, pool_v, dense_k, dense_v, table, lengths, q, k_new, v_new,
     dims) = _paged_case(jax.random.PRNGKey(1))
    B, K, hd = dims["B"], dims["K"], dims["hd"]
    layer = 1
    out = flash_decode_append_paged(
        q, _split_paged(pool_k), _split_paged(pool_v), jnp.int32(layer),
        k_new, v_new, table, lengths, interpret=True)
    gathered_k = dense_k[layer][table].reshape(B, -1, K, hd)
    gathered_v = dense_v[layer][table].reshape(B, -1, K, hd)
    reference = attention_decode_append(q, gathered_k, gathered_v,
                                        k_new, v_new, lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(reference),
                               atol=1e-5, rtol=1e-5)


def test_paged_append_int8_pools_dequantized_in_kernel():
    """int8 scale pools ride their pages and dequantize in-kernel --
    exact relative to dequantize-then-dense (no weight quantization on
    this path, the flash-decode discipline)."""
    (pool_k, pool_v, dense_k, dense_v, table, lengths, q, k_new, v_new,
     dims) = _paged_case(jax.random.PRNGKey(2), quantized=True)
    B, K, hd = dims["B"], dims["K"], dims["hd"]
    layer = 0
    out = flash_decode_append_paged(
        q, _split_paged(pool_k), _split_paged(pool_v), jnp.int32(layer),
        k_new, v_new, table, lengths, interpret=True)
    reference = attention_decode_append(
        q, dense_k[layer][table].reshape(B, -1, K, hd),
        dense_v[layer][table].reshape(B, -1, K, hd), k_new, v_new,
        lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(reference),
                               atol=1e-4, rtol=1e-4)


def test_paged_append_bf16_tolerance():
    (pool_k, pool_v, dense_k, dense_v, table, lengths, q, k_new, v_new,
     dims) = _paged_case(jax.random.PRNGKey(3), dtype=jnp.bfloat16)
    B, K, hd = dims["B"], dims["K"], dims["hd"]
    layer = 1
    out = flash_decode_append_paged(
        q, _split_paged(pool_k), _split_paged(pool_v), jnp.int32(layer),
        k_new, v_new, table, lengths, interpret=True)
    reference = attention_decode_append(
        q, dense_k[layer][table].reshape(B, -1, K, hd).astype(q.dtype),
        dense_v[layer][table].reshape(B, -1, K, hd).astype(q.dtype),
        k_new, v_new, lengths)
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32),
        np.asarray(reference, dtype=np.float32), atol=6e-2, rtol=6e-2)


def test_mixed_quantization_paged_views_rejected():
    (pool_k, pool_v, *_rest, table_lengths) = _paged_case(
        jax.random.PRNGKey(4))
    (pool_kq, pool_vq, _, _, table, lengths, q, k_new, v_new,
     _) = _paged_case(jax.random.PRNGKey(4), quantized=True)
    with pytest.raises(ValueError, match="quantization state"):
        flash_decode_append_paged(
            q, _split_paged(pool_kq), _split_paged(pool_v),
            jnp.int32(0), k_new, v_new, table, lengths, interpret=True)


# -- decode_step / decode_loop integration ----------------------------------

def _fully_mapped_paged_cache(config, batch, page_tokens):
    """Paged cache with every slot's logical pages mapped to distinct
    physical pages (full provisioning, deterministic layout)."""
    cache = init_paged_cache(config, batch, config.max_seq, page_tokens)
    pps = config.max_seq // page_tokens
    table = np.arange(1, batch * pps + 1, dtype=np.int32) \
        .reshape(batch, pps)
    cache["page_table"] = jnp.asarray(table)
    return cache


def _paged_decode_logits(config, steps=6):
    params = llama.init_params(jax.random.PRNGKey(0), config)
    cache = _fully_mapped_paged_cache(config, 2, 32)
    lengths = jnp.zeros(2, dtype=jnp.int32)
    outs = []
    for step in range(steps):
        tokens = jnp.asarray([10 + step, 20 + step], dtype=jnp.int32)
        logits, cache = llama.decode_step(params, config, tokens, cache,
                                          lengths)
        lengths = lengths + 1
        outs.append(logits)
    return jnp.stack(outs)


def test_decode_step_paged_kernel_matches_dense_gather():
    """decode_step on a paged cache with decode_attention='flash' (the
    request that used to RAISE) evolves the same cache and produces the
    same logits as the dense gather path over multiple steps."""
    base = llama.LlamaConfig.tiny(vocab_size=64, max_seq=128)
    dense = _paged_decode_logits(
        dataclasses.replace(base, decode_attention="dense"))
    flash = _paged_decode_logits(
        dataclasses.replace(base, decode_attention="flash"))
    np.testing.assert_allclose(np.asarray(flash), np.asarray(dense),
                               atol=5e-2, rtol=2e-2)


def test_decode_loop_paged_kernel_token_identical():
    """The device-resident serving loop on a paged cache: paged-kernel
    vs reference backends emit IDENTICAL token streams at temperature 0
    (greedy ties broken the same way on this seed)."""
    base = llama.LlamaConfig.tiny(vocab_size=64, max_seq=128)
    streams = {}
    for name, attention in (("kernel", "flash"), ("reference", "dense")):
        config = dataclasses.replace(base, decode_attention=attention)
        params = llama.init_params(jax.random.PRNGKey(0), config)
        cache = _fully_mapped_paged_cache(config, 2, 32)
        out = llama.decode_loop(
            params, config,
            jnp.asarray([7, 11], dtype=jnp.int32), cache,
            jnp.asarray([1, 1], dtype=jnp.int32),
            jnp.ones(2, dtype=bool),
            jnp.full((2,), 12, dtype=jnp.int32),
            jnp.zeros(2, dtype=jnp.float32),
            jnp.full((2, 1), -1, dtype=jnp.int32),
            jnp.full((2, 1), -1, dtype=jnp.int32),
            jax.random.PRNGKey(5), ring=8)
        emitted, counts = out[0], out[1]
        streams[name] = (np.asarray(emitted), np.asarray(counts))
    assert np.array_equal(streams["kernel"][1], streams["reference"][1])
    assert np.array_equal(streams["kernel"][0], streams["reference"][0])


def test_decode_backend_capability_probe():
    """The probe replaces the old raise: paged + explicit flash is the
    paged kernel; auto follows extent/threshold/structure; distributed
    and dense force the reference path."""
    assert decode_backend("flash", paged=True,
                          page_tokens=64) == "paged-kernel"
    assert decode_backend("auto", paged=True, extent=2048,
                          threshold=1024,
                          page_tokens=64) == "paged-kernel"
    assert decode_backend("auto", paged=True, extent=256,
                          threshold=1024, page_tokens=64) == "reference"
    assert decode_backend("auto", paged=True, extent=2048,
                          threshold=1024, page_tokens=6) == "reference"
    assert decode_backend("flash") == "dense-flash"
    assert decode_backend("auto", extent=2048,
                          threshold=1024) == "dense-flash"
    assert decode_backend("auto", extent=2000,
                          threshold=1024) == "reference"   # % 128
    assert decode_backend("flash", paged=True, distributed=True,
                          page_tokens=64) == "reference"
    assert decode_backend("dense", extent=8192) == "reference"


# -- batched chunk-verify ---------------------------------------------------

def _verify_reference(k_rows, v_rows, q, k_new, v_new, starts,
                      positions):
    """The dense concat-attention _chunk_verify computes, verbatim."""
    b, t = k_rows.shape[:2]
    s = q.shape[1]
    k_all = jnp.concatenate([k_rows, k_new], axis=1)
    v_all = jnp.concatenate([v_rows, v_new], axis=1)
    kv_positions = jnp.concatenate(
        [jnp.broadcast_to(jnp.arange(t)[None, :], (b, t)), positions],
        axis=1)
    valid = jnp.concatenate(
        [jnp.arange(t)[None, :] < starts[:, None],
         jnp.ones((b, s), dtype=bool)], axis=1)
    return attention_prefill(q, k_all, v_all, positions,
                             kv_length_mask=valid,
                             kv_positions=kv_positions)


def test_chunk_verify_kernel_matches_dense():
    """flash_verify_append == the dense concat path at f32, across a
    zero-start row, a mid-cache row and a trash-clamped boundary row --
    stacked AND paged cache forms, raw and int8."""
    key = jax.random.PRNGKey(6)
    L, B, K, G, hd, S, T = 2, 3, 2, 2, 16, 5, 128
    C, H = K * hd, K * G
    starts = jnp.asarray([0, 17, T - 1], dtype=jnp.int32)
    positions = jnp.minimum(starts[:, None] + jnp.arange(S)[None, :],
                            T - 1)
    q = jax.random.normal(jax.random.fold_in(key, 1), (B, S, H, hd),
                          dtype=jnp.float32)
    k_new = jax.random.normal(jax.random.fold_in(key, 2), (B, S, K, hd),
                              dtype=jnp.float32)
    v_new = jax.random.normal(jax.random.fold_in(key, 3), (B, S, K, hd),
                              dtype=jnp.float32)

    # stacked raw
    k_cache = jax.random.normal(jax.random.fold_in(key, 4), (L, B, T, C),
                                dtype=jnp.float32)
    v_cache = jax.random.normal(jax.random.fold_in(key, 5), (L, B, T, C),
                                dtype=jnp.float32)
    layer = 1
    out = flash_verify_append(q, (k_cache, None), (v_cache, None),
                              jnp.int32(layer), k_new, v_new, starts,
                              positions, interpret=True)
    reference = _verify_reference(
        k_cache[layer].reshape(B, T, K, hd),
        v_cache[layer].reshape(B, T, K, hd), q, k_new, v_new, starts,
        positions)
    np.testing.assert_allclose(np.asarray(out), np.asarray(reference),
                               atol=1e-5, rtol=1e-5)

    # stacked int8: in-kernel dequant vs dequantize-then-dense
    raw_k = jax.random.normal(jax.random.fold_in(key, 6),
                              (L, B, T, K, hd), dtype=jnp.float32)
    raw_v = jax.random.normal(jax.random.fold_in(key, 7),
                              (L, B, T, K, hd), dtype=jnp.float32)
    qk, qv = quantize_kv(raw_k), quantize_kv(raw_v)
    k_view = (qk["int8"].reshape(L, B, T, C),
              qk["scale"][..., 0].transpose(0, 1, 3, 2)
              .astype(jnp.float32))
    v_view = (qv["int8"].reshape(L, B, T, C),
              qv["scale"][..., 0].transpose(0, 1, 3, 2)
              .astype(jnp.float32))
    out = flash_verify_append(q, k_view, v_view, jnp.int32(layer),
                              k_new, v_new, starts, positions,
                              interpret=True)
    reference = _verify_reference(
        dequantize_kv(qk, jnp.float32)[layer],
        dequantize_kv(qv, jnp.float32)[layer], q, k_new, v_new, starts,
        positions)
    np.testing.assert_allclose(np.asarray(out), np.asarray(reference),
                               atol=1e-4, rtol=1e-4)

    # paged: table walked in-kernel
    P, pt, pps = 13, 32, 4
    pool_k = jax.random.normal(jax.random.fold_in(key, 8),
                               (L, P, pt, C), dtype=jnp.float32)
    pool_v = jax.random.normal(jax.random.fold_in(key, 9),
                               (L, P, pt, C), dtype=jnp.float32)
    table = jnp.asarray([[1, 2, 3, 0], [4, 5, 6, 7], [8, 9, 10, 11]],
                        dtype=jnp.int32)
    out = flash_verify_append(q, (pool_k, None), (pool_v, None),
                              jnp.int32(layer), k_new, v_new, starts,
                              positions, page_table=table,
                              interpret=True)
    reference = _verify_reference(
        pool_k[layer][table].reshape(B, pps * pt, K, hd),
        pool_v[layer][table].reshape(B, pps * pt, K, hd), q, k_new,
        v_new, starts, positions)
    np.testing.assert_allclose(np.asarray(out), np.asarray(reference),
                               atol=1e-5, rtol=1e-5)


def test_chunk_verify_wired_into_speculative_loop():
    """_chunk_verify with use_flash routes through the kernel and
    produces the same logits and cache as the dense concat path."""
    from aiko_services_tpu.models.llama import _chunk_verify

    config = dataclasses.replace(
        llama.LlamaConfig.tiny(vocab_size=64, max_seq=128),
        dtype="float32")
    params = llama.init_params(jax.random.PRNGKey(0), config)
    chunk = jnp.asarray([[5, 9, 2], [1, 3, 3]], dtype=jnp.int32)
    starts = jnp.asarray([4, 19], dtype=jnp.int32)
    trash = config.max_seq - 1

    outs = {}
    for use_flash in (False, True):
        cache = llama.init_cache(config, 2)
        logits, new_cache = jax.jit(
            lambda c: _chunk_verify(params, config, chunk, c, starts,
                                    trash, use_flash=use_flash))(cache)
        outs[use_flash] = (logits, new_cache)
    np.testing.assert_allclose(np.asarray(outs[True][0]),
                               np.asarray(outs[False][0]),
                               atol=1e-4, rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(outs[True][1]),
                    jax.tree_util.tree_leaves(outs[False][1])):
        np.testing.assert_allclose(
            np.asarray(a, dtype=np.float32),
            np.asarray(b, dtype=np.float32), atol=1e-5, rtol=1e-5)


# -- fused int8 dequant-matmul ----------------------------------------------

def test_int8_matmul_matches_xla():
    """Exact on exactly-representable inputs; f32 accumulation-order
    tolerance on gaussian bf16 -- vs the XLA reference
    ``(x @ w.astype) * scale`` (llama.matmul's non-kernel path)."""
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.integers(-7, 8, (96, 260)), jnp.float32)
    leaf = quantize_weight(w)
    x = jnp.asarray(rng.integers(-3, 4, (5, 96)), jnp.float32)
    reference = (x @ leaf["int8"].astype(x.dtype)) \
        * leaf["scale"].astype(x.dtype)
    out = int8_matmul(x, leaf["int8"], leaf["scale"], block_f=128,
                      block_d=32, interpret=True)
    assert np.array_equal(np.asarray(out), np.asarray(reference))

    xb = jax.random.normal(jax.random.PRNGKey(0), (4, 96), jnp.bfloat16)
    reference = (xb @ leaf["int8"].astype(xb.dtype)) \
        * leaf["scale"].astype(xb.dtype)
    out = int8_matmul(xb, leaf["int8"], leaf["scale"], interpret=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32),
        np.asarray(reference, dtype=np.float32), atol=1e-1, rtol=2e-2)


def test_int8_matmul_serves_the_unembed():
    """decode_step logits with matmul_kernel='pallas' (the fused
    kernel on the quantized unembed, interpret mode here) match
    matmul_kernel='off' (XLA) on the same int8 tree."""
    base = llama.LlamaConfig.tiny(vocab_size=64, max_seq=64)
    params = quantize_params(
        llama.init_params(jax.random.PRNGKey(0), base))
    tokens = jnp.asarray([3, 5], dtype=jnp.int32)
    lengths = jnp.zeros(2, dtype=jnp.int32)
    logits = {}
    for mode in ("off", "pallas"):
        config = dataclasses.replace(base, matmul_kernel=mode)
        cache = llama.init_cache(config, 2)
        out, _ = llama.decode_step(params, config, tokens, cache,
                                   lengths)
        logits[mode] = np.asarray(out, dtype=np.float32)
    np.testing.assert_allclose(logits["pallas"], logits["off"],
                               atol=5e-2, rtol=5e-2)
    assert matmul_backend("off") == "reference"
    assert matmul_backend("pallas") == "pallas-int8"


# -- on-TPU top-k -----------------------------------------------------------

def test_topk_matches_lax():
    """Values AND indices equal lax.top_k across shapes, block sizes
    and dtypes -- including the ragged tail and a bf16 operand."""
    rng = np.random.default_rng(1)
    for (b, v, k, block_v) in ((5, 700, 8, 256), (1, 64, 3, 2048),
                               (17, 5000, 16, 1024), (8, 128, 128, 128)):
        x = jnp.asarray(rng.normal(size=(b, v)), jnp.float32)
        values, indices = pallas_topk(x, k, block_v=block_v,
                                      interpret=True)
        lax_values, lax_indices = jax.lax.top_k(x, k)
        assert np.array_equal(np.asarray(values), np.asarray(lax_values))
        assert np.array_equal(np.asarray(indices),
                              np.asarray(lax_indices))
    xb = jnp.asarray(rng.normal(size=(9, 333)), jnp.bfloat16)
    values, indices = pallas_topk(xb, 5, block_v=128, interpret=True)
    lax_values, lax_indices = jax.lax.top_k(xb, 5)
    assert values.dtype == jnp.bfloat16
    assert np.array_equal(np.asarray(values, dtype=np.float32),
                          np.asarray(lax_values, dtype=np.float32))
    assert np.array_equal(np.asarray(indices), np.asarray(lax_indices))


def test_int8_matmul_blocks_over_m():
    """Prefill-shaped M (B*S rows) exercises the M-blocking that keeps
    the kernel's VMEM tiles bounded on TPU -- with block_m smaller than
    M, partial tiles and the ragged M tail must still match XLA."""
    rng = np.random.default_rng(2)
    w = jnp.asarray(rng.integers(-7, 8, (64, 384)), jnp.float32)
    leaf = quantize_weight(w)
    x = jnp.asarray(rng.integers(-3, 4, (300, 64)), jnp.float32)
    reference = (x @ leaf["int8"].astype(x.dtype)) \
        * leaf["scale"].astype(x.dtype)
    out = int8_matmul(x, leaf["int8"], leaf["scale"], block_m=128,
                      block_f=128, block_d=32, interpret=True)
    assert np.array_equal(np.asarray(out), np.asarray(reference))


def test_topk_masked_rows_match_lax():
    """Rows with fewer than k finite values (padded logits, masked ANN
    scores): the consumed-column mask keeps extracted (-inf, index)
    candidates DISTINCT, so indices stay unique and match lax.top_k's
    ascending order over the -inf tail (value-only masking re-extracted
    the same entry and emitted duplicates)."""
    x = jnp.full((3, 256), -jnp.inf)
    x = x.at[0, 3].set(1.0).at[0, 7].set(2.0)       # 2 finite < k=4
    x = x.at[1, 200].set(5.0)                       # 1 finite, tail block
    values, indices = pallas_topk(x, 4, block_v=128, interpret=True)
    lax_values, lax_indices = jax.lax.top_k(x, 4)
    assert np.array_equal(np.asarray(indices), np.asarray(lax_indices))
    assert np.array_equal(np.asarray(values), np.asarray(lax_values))
    for row in np.asarray(indices):
        assert len(set(row.tolist())) == 4          # no duplicates


def test_topk_tie_breaking_is_stable():
    """Equal values resolve to the LOWEST index first -- lax.top_k's
    stable contract, pinned explicitly (ties across block boundaries
    are exactly what the running-state merge could get wrong)."""
    x = jnp.zeros((3, 600)).at[:, 5].set(2.0).at[:, 300].set(2.0) \
        .at[:, 10].set(1.0).at[:, 599].set(1.0)
    values, indices = pallas_topk(x, 4, block_v=128, interpret=True)
    lax_values, lax_indices = jax.lax.top_k(x, 4)
    assert np.array_equal(np.asarray(indices), np.asarray(lax_indices))
    assert np.array_equal(np.asarray(values), np.asarray(lax_values))
    assert list(np.asarray(indices[0])) == [5, 300, 10, 599]


def test_paged_kernel_rejects_misaligned_page_size():
    """A forced paged-kernel request with a sublane-misaligned page
    size fails by name on every backend instead of surfacing an opaque
    Mosaic tiling error on TPU (the 'auto' probe never routes such a
    config here)."""
    L, P, pt, B, C = 1, 3, 12, 2, 32
    pool = jnp.zeros((L, P, pt, C), dtype=jnp.float32)
    table = jnp.zeros((B, 2), dtype=jnp.int32)
    q_pad = jnp.zeros((B, 4, C), dtype=jnp.float32)
    with pytest.raises(ValueError, match="multiple of 8"):
        flash_decode_attention_paged(q_pad, pool, pool, None, None,
                                     jnp.int32(0), table,
                                     jnp.zeros(B, dtype=jnp.int32),
                                     interpret=True)


def test_sample_top_k_bounded_at_build_and_create():
    """sample_top_k above the kernel's 128-lane cap fails at batcher
    build AND at create-time domain validation -- not mid-serving on
    TPU (the CPU path would happily serve it via lax.top_k)."""
    from aiko_services_tpu.analysis.params import \
        validate_element_parameters
    from aiko_services_tpu.models.batching import ContinuousBatcher

    config = llama.LlamaConfig.tiny(vocab_size=64, max_seq=64)
    params = llama.init_params(jax.random.PRNGKey(0), config)
    with pytest.raises(ValueError, match="128"):
        ContinuousBatcher(params, config, max_slots=2,
                          sample_top_k=200)
    findings = validate_element_parameters(
        "LLM", {"sample_top_k": 200}, "p: llm",
        module="aiko_services_tpu.elements.llm")
    assert [f.rule for f in findings] == ["bad-parameter"]
    assert "<= 128" in findings[0].message


def test_topk_rejects_bad_k():
    x = jnp.zeros((2, 64))
    with pytest.raises(ValueError, match="k="):
        pallas_topk(x, 0, interpret=True)
    with pytest.raises(ValueError, match="k="):
        pallas_topk(x, 129, interpret=True)


def test_select_tokens_top_k_restricts_sampling():
    """top_k=1 at temperature > 0 equals greedy (the candidate set is
    the argmax); top_k=0 keeps the full categorical; greedy rows are
    unaffected by top_k.  The dispatching ops.topk interface resolves
    to lax off-TPU, so this exercises the serving wiring."""
    key = jax.random.PRNGKey(0)
    logits = jax.random.normal(jax.random.fold_in(key, 1), (4, 64))
    temps = jnp.asarray([0.0, 0.7, 1.0, 0.3])
    greedy = np.asarray(jnp.argmax(logits, axis=-1))
    top1 = np.asarray(llama.select_tokens(key, logits, temps, top_k=1))
    assert np.array_equal(top1, greedy)
    # top_k restricts every sampled row's token to the k candidates
    k = 4
    _, candidates = topk(jnp.asarray(logits, jnp.float32), k,
                         kernel=False)
    for draw in range(5):
        out = np.asarray(llama.select_tokens(
            jax.random.fold_in(key, draw), logits, temps, top_k=k))
        for row in range(4):
            assert out[row] in np.asarray(candidates[row])


def test_batcher_sample_top_k_round_trip():
    """ContinuousBatcher(sample_top_k=1) at temperature>0 emits the
    greedy stream (top-1 == argmax), through the real serving loop."""
    from aiko_services_tpu.models.batching import (ContinuousBatcher,
                                                   Request)

    config = llama.LlamaConfig.tiny(vocab_size=64, max_seq=64)
    params = llama.init_params(jax.random.PRNGKey(0), config)
    streams = {}
    for label, kwargs in (
            ("greedy", dict()),
            ("top1", dict(sample_top_k=1))):
        batcher = ContinuousBatcher(params, config, max_slots=2,
                                    decode_block_tokens=8, **kwargs)
        collected = []
        temperature = 0.0 if label == "greedy" else 0.9
        batcher.submit(Request(
            "r", [5, 9, 2, 7], max_new_tokens=10,
            temperature=temperature,
            emit=lambda rid, tok, fin: collected.append(tok)))
        batcher.run_until_drained(max_steps=200)
        streams[label] = collected
    assert streams["top1"] == streams["greedy"]