"""jax.profiler integration (SURVEY §5.1 TPU-equiv): process trace plus
per-element TraceAnnotations driven by the pipeline hooks."""

import os
import queue

from conftest import run_until
from aiko_services_tpu.pipeline import Pipeline
from aiko_services_tpu.tpu import Profiler, profile_trace

ELEMENTS = "tests/pipeline_elements.py"


def _definition():
    def element(name, cls, inputs, outputs):
        return {"name": name,
                "input": [{"name": n} for n in inputs],
                "output": [{"name": n} for n in outputs],
                "deploy": {"local": {"module": ELEMENTS,
                                     "class_name": cls}}}
    return {"version": 0, "name": "p_prof", "runtime": "jax",
            "graph": ["(A B)"],
            "elements": [element("A", "ElementA", ["a"], ["a"]),
                         element("B", "ElementB", ["a"], ["b"])]}


def _run_frame(runtime, pipeline, frame_data):
    responses = queue.Queue()
    pipeline.process_frame_local(frame_data, queue_response=responses)
    run_until(runtime, lambda: not responses.empty())
    assert not responses.empty()


def test_element_annotations_balanced(runtime, tmp_path):
    pipeline = Pipeline(_definition(), runtime=runtime)
    profiler = Profiler()
    profiler.start(str(tmp_path / "trace"))
    profiler.attach(pipeline)
    try:
        _run_frame(runtime, pipeline, {"a": 1})
        _run_frame(runtime, pipeline, {"a": 2})
    finally:
        profiler.detach()
        assert not profiler._open       # every span closed
        profiler.stop()
    assert not profiler.active
    # post hook fired once per element per frame
    assert pipeline._hooks["pipeline.process_element_post:0"].count == 4
    # a trace was actually written (plugins/profile/... under logdir)
    produced = [os.path.join(root, f)
                for root, _, files in os.walk(tmp_path) for f in files]
    assert produced, "jax.profiler wrote no trace files"


def test_profile_trace_context_manager(runtime, tmp_path):
    pipeline = Pipeline(_definition(), runtime=runtime)
    with profile_trace(str(tmp_path / "t2"), pipeline) as profiler:
        assert profiler.active
        _run_frame(runtime, pipeline, {"a": 3})
    assert not profiler.active
    assert profiler._pipelines == []


def test_unwind_closes_nested_pairs_innermost_first():
    """Regression (ISSUE 4 satellite): detach()/_unwind() must close
    nested ``compile:``/``segment:`` pairs INNERMOST-first.  Raw
    popitem() order scrambles when a re-entered key moved an outer
    ``compile:`` span after its inner ``segment:`` span in insertion
    order -- the outer annotation then exited first and corrupted the
    xprof nesting."""
    profiler = Profiler()
    exits = []

    class FakeAnnotation:
        def __init__(self, name):
            self.name = name

        def __exit__(self, *args):
            exits.append(self.name)

    base = ("S", "stream", 0)
    # Adversarial insertion order: the outer compile: span sits AFTER
    # its inner segment: span (re-entry scramble), with an element span
    # opened in between.
    profiler._open[("segment",) + base] = FakeAnnotation("segment:S")
    profiler._open[("E", "stream", 0)] = FakeAnnotation("element:E")
    profiler._open[("compile",) + base] = FakeAnnotation("compile:S")
    profiler._unwind()
    assert not profiler._open
    assert exits.index("segment:S") < exits.index("compile:S"), exits
    # Non-compile spans still close in reverse insertion order.
    assert exits[0] == "element:E"


def test_dangling_annotation_unwound(runtime, tmp_path):
    """An element that raises must not leak its open span into later
    elements (the engine pairs the enter hook with an ERROR post on
    failure paths; detach unwinds anything that still dangles)."""
    definition = _definition()
    definition["elements"][1]["deploy"]["local"]["class_name"] = "Raiser"
    definition["graph"] = ["(A B)"]
    pipeline = Pipeline(definition, runtime=runtime)
    with profile_trace(str(tmp_path / "t3"), pipeline) as profiler:
        responses = queue.Queue()
        pipeline.process_frame_local({"a": 1}, queue_response=responses)
        run_until(runtime, lambda: not responses.empty())
        assert len(profiler._open) <= 1      # only B's dangling span
        _run_frame(runtime, pipeline, {"a": 1})
    assert not profiler._open
