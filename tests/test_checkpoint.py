"""Checkpoint/resume: sharded save + restore onto a mesh (absent in the
reference -- SURVEY.md section 5.4 required addition)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiko_services_tpu.models import llama
from aiko_services_tpu.models.checkpoint import (Checkpointer, restore_pytree,
                                                 save_pytree)
from aiko_services_tpu.parallel import MeshPlan, make_mesh


def test_roundtrip_simple(tmp_path):
    state = {"w": jnp.arange(8.0), "b": jnp.ones((2, 3))}
    save_pytree(tmp_path / "ck", state)
    restored = restore_pytree(tmp_path / "ck", template=state)
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.asarray(state["w"]))
    np.testing.assert_array_equal(np.asarray(restored["b"]),
                                  np.asarray(state["b"]))


def test_sharded_restore_onto_mesh(tmp_path):
    """Llama params saved sharded, restored directly sharded."""
    config = llama.LlamaConfig.tiny(vocab_size=64, max_seq=32)
    plan = MeshPlan(make_mesh({"fsdp": 2, "tp": 4}))
    specs = llama.partition_specs(config)
    params = plan.put(llama.init_params(jax.random.PRNGKey(0), config),
                      specs)

    with Checkpointer(tmp_path / "ck") as ckpt:
        ckpt.save(10, {"params": params}, metadata={"loss": 1.5},
                  wait=True)
        restored = ckpt.restore(template={"params": params},
                                plan=plan, specs={"params": specs})
        meta = ckpt.metadata()

    leaf = restored["params"]["layers"]["wq"]
    assert leaf.sharding.mesh.shape["tp"] == 4
    np.testing.assert_array_equal(
        np.asarray(leaf, dtype=np.float32),
        np.asarray(params["layers"]["wq"], dtype=np.float32))
    assert meta["loss"] == 1.5
    assert meta["step"] == 10


def test_retention_and_latest(tmp_path):
    with Checkpointer(tmp_path / "ck", keep=2) as ckpt:
        for step in (1, 2, 3):
            ckpt.save(step, {"x": jnp.full((4,), float(step))}, wait=True)
        assert ckpt.latest_step == 3
        assert len(ckpt.all_steps()) == 2          # keep=2
        restored = ckpt.restore(template={"x": jnp.zeros((4,))})
        np.testing.assert_array_equal(np.asarray(restored["x"]),
                                      [3.0, 3.0, 3.0, 3.0])


def test_restore_empty_raises(tmp_path):
    with Checkpointer(tmp_path / "ck") as ckpt:
        with pytest.raises(FileNotFoundError):
            ckpt.restore()
