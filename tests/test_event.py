"""Event engine tests: timers, mailbox priority, thread-safe posting."""

import threading
import time

from aiko_services_tpu.runtime import EventEngine


def test_timer_fires():
    engine = EventEngine()
    fired = []
    engine.add_oneshot_timer(lambda: fired.append(time.monotonic()), 0.01)
    engine.run(until=lambda: bool(fired), timeout=2.0)
    assert fired


def test_periodic_timer():
    engine = EventEngine()
    count = []
    engine.add_timer_handler(lambda: count.append(1), 0.005)
    engine.run(until=lambda: len(count) >= 3, timeout=2.0)
    assert len(count) >= 3


def test_mailbox_priority_preemption():
    """Items in the first-registered (control) mailbox drain before items
    in later mailboxes, even when queued afterwards."""
    engine = EventEngine()
    order = []
    engine.add_mailbox_handler(lambda item: order.append(("control", item)),
                               "control")
    engine.add_mailbox_handler(lambda item: order.append(("in", item)), "in")
    engine.mailbox_put("in", 1)
    engine.mailbox_put("in", 2)
    engine.mailbox_put("control", "c1")
    engine.run(until=lambda: len(order) == 3, timeout=2.0)
    assert order[0] == ("control", "c1")
    assert [o for o in order if o[0] == "in"] == [("in", 1), ("in", 2)]


def test_post_from_thread():
    engine = EventEngine()
    seen = []

    def worker():
        time.sleep(0.02)
        engine.post(seen.append, "from-thread")

    threading.Thread(target=worker, daemon=True).start()
    engine.run(until=lambda: bool(seen), timeout=2.0)
    assert seen == ["from-thread"]


def test_latency_under_reference_tick():
    """The reference's 10 ms tick is its latency floor; ours must be far
    below it (BASELINE.md: event-loop tick)."""
    engine = EventEngine()
    stamps = {}

    def sender():
        time.sleep(0.02)
        stamps["sent"] = time.perf_counter()
        engine.mailbox_put("mb", None)

    engine.add_mailbox_handler(
        lambda item: stamps.__setitem__("recv", time.perf_counter()), "mb")
    threading.Thread(target=sender, daemon=True).start()
    engine.run(until=lambda: "recv" in stamps, timeout=2.0)
    latency = stamps["recv"] - stamps["sent"]
    assert latency < 0.005, f"cross-thread latency {latency * 1e3:.2f} ms"


def test_terminate_from_handler():
    engine = EventEngine()
    engine.add_oneshot_timer(engine.terminate, 0.01)
    start = time.monotonic()
    engine.run(timeout=5.0)
    assert time.monotonic() - start < 1.0
