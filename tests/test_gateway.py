"""Gateway front door (ISSUE 12): a REAL WebSocket client (stdlib,
loopback) streaming frames through a placed multi-stage pipeline --
session lifecycle (open/attach/backpressure/disconnect), in-order
delivery, HTTP request/response, per-tenant rate limiting, per-tenant/
class observability, and the open-loop load generator's shed-fairness
contract under 2x overload."""

import json
import queue
import socket
import threading
import time
import urllib.request

import pytest

from conftest import run_until

from aiko_services_tpu.gateway.client import GatewayClient
from aiko_services_tpu.gateway.loadgen import LoadSpec, run_loadgen
from aiko_services_tpu.gateway.server import decode_data, json_safe
from aiko_services_tpu.pipeline import Pipeline

COMMON = "aiko_services_tpu.elements.common"


def stage(name, busy_ms=5.0, factor=2.0, devices=4):
    return {"name": name, "input": [{"name": "x"}],
            "output": [{"name": "x"}],
            "parameters": {"busy_ms": busy_ms, "factor": factor},
            "placement": {"devices": devices},
            "deploy": {"local": {"module": COMMON,
                                 "class_name": "StageWork"}}}


def gateway_pipeline(runtime, qos=None, busy_ms=5.0):
    parameters = {"gateway": "on"}
    if qos is not None:
        parameters["qos"] = qos
    return Pipeline(
        {"version": 0, "name": "gw", "runtime": "jax",
         "graph": ["(detect llm)"],
         "parameters": parameters,
         "elements": [stage("detect", busy_ms),
                      stage("llm", busy_ms, factor=3.0)]},
        runtime=runtime)


def in_thread(target):
    """Run a blocking client interaction off the loop thread; returns
    (thread, box) where box collects the return value or error."""
    box: dict = {}

    def body():
        try:
            box["value"] = target()
        except Exception as error:      # surfaced by the test
            box["error"] = error
    thread = threading.Thread(target=body, daemon=True)
    thread.start()
    return thread, box


def finish(runtime, thread, box, timeout=60.0):
    run_until(runtime, lambda: not thread.is_alive(), timeout=timeout)
    assert not thread.is_alive(), "client interaction hung"
    if "error" in box:
        raise box["error"]
    return box.get("value")


# -- codec helpers ----------------------------------------------------------

def test_decode_data_and_json_safe_roundtrip():
    import numpy as np
    decoded = decode_data({"x": [[1.0, 2.0], [3.0, 4.0]],
                           "n": [1, 2, 3], "s": "hi", "f": 2.5,
                           "t": {"__tensor__": [1, 2],
                                 "dtype": "int8"}})
    assert decoded["x"].dtype == np.float32
    assert decoded["x"].shape == (2, 2)
    assert decoded["n"].dtype == np.int32
    assert decoded["s"] == "hi" and decoded["f"] == 2.5
    assert decoded["t"].dtype == np.int8
    safe = json_safe({"x": np.ones((2,), np.float32),
                      "o": object(), "b": b"ab"})
    assert safe["x"] == [1.0, 1.0]
    assert safe["o"] == "<object>" and safe["b"] == "ab"


# -- the tier-1 acceptance path ---------------------------------------------

def test_ws_client_streams_n_frames_in_order(runtime):
    """ISSUE 12 acceptance: a real WebSocket client opens a session,
    streams N frames through a placed two-stage pipeline, and
    receives N in-order results -- stdlib client, loopback, no
    external broker."""
    pipeline = gateway_pipeline(runtime)
    n_frames = 8
    # the front door is a discoverable capability of the Service: the
    # registrar record advertises it like the tensor pipe's tag.
    assert any(tag == f"gateway=127.0.0.1:{pipeline.gateway.port}"
               for tag in pipeline.tags), pipeline.tags
    assert pipeline.share["gateway_port"] == pipeline.gateway.port

    def interact():
        with GatewayClient("127.0.0.1", pipeline.gateway.port) as c:
            opened = c.open(session="s1", tenant="t1")
            assert opened["attached"] is False
            for i in range(n_frames):
                c.send_frame({"x": [float(i + 1)] * 4})
            return [c.next_result() for _ in range(n_frames)]

    thread, box = in_thread(interact)
    results = finish(runtime, thread, box)
    assert [r["frame"] for r in results] == list(range(n_frames))
    for i, result in enumerate(results):
        assert result["ok"], result
        # detect *2 then llm *3: the engine really ran the frame
        assert result["data"]["x"][0] == pytest.approx(6.0 * (i + 1))
    run_until(runtime, lambda: not pipeline.streams, timeout=30.0)
    assert pipeline.gateway.session_count() == 0


def test_ws_attach_takes_over_session(runtime):
    """``open`` with an existing session id attaches: the stream (and
    its frame numbering) continues; the old connection's death no
    longer destroys the session."""
    pipeline = gateway_pipeline(runtime)
    port = pipeline.gateway.port

    def interact():
        c1 = GatewayClient("127.0.0.1", port)
        c1.open(session="s2")
        c1.send_frame({"x": [1.0]})
        first = c1.next_result()
        # attach without the minted token is a refused hijack, not a
        # takeover -- session ids are client-chosen guessable strings.
        hijacker = GatewayClient("127.0.0.1", port)
        hijacker.send({"op": "open", "session": "s2",
                       "tenant": "mallory"})
        refused = hijacker.recv(timeout=10.0)
        hijacker.sock.close()
        assert refused["op"] == "error", refused
        c2 = GatewayClient("127.0.0.1", port)
        opened = c2.open(session="s2", token=c1.token)
        assert opened["attached"] is True
        c1.sock.close()                 # abrupt: no close handshake
        time.sleep(0.2)                 # let the server notice
        c2.send_frame({"x": [2.0]})
        second = c2.next_result()
        c2.close()
        return first, second

    thread, box = in_thread(interact)
    first, second = finish(runtime, thread, box)
    assert first["frame"] == 0 and second["frame"] == 1, \
        "attach did not continue the same stream"
    run_until(runtime, lambda: not pipeline.streams, timeout=30.0)
    assert not pipeline.streams


def test_ws_backpressure_busy_at_window(runtime):
    """The per-session window bounds in-flight frames: the overflow
    frame gets ``busy`` instead of queueing unboundedly."""
    pipeline = gateway_pipeline(runtime, busy_ms=60.0)

    def interact():
        with GatewayClient("127.0.0.1", pipeline.gateway.port) as c:
            c.open(session="s3", window=1)
            ops = []
            for i in range(3):
                c.send_frame({"x": [float(i)]}, tag=i)
            deadline = time.monotonic() + 30.0
            results = 0
            while results < 1 and time.monotonic() < deadline:
                message = c.recv(timeout=10.0)
                ops.append(message["op"])
                if message["op"] == "result":
                    results += 1
            return ops

    thread, box = in_thread(interact)
    ops = finish(runtime, thread, box)
    assert "busy" in ops, ops


def test_ws_disconnect_mid_stream_cleans_up(runtime):
    """A dangling disconnect destroys the session's pipeline stream:
    no leaked streams, no leaked sessions."""
    pipeline = gateway_pipeline(runtime, busy_ms=30.0)

    def interact():
        c = GatewayClient("127.0.0.1", pipeline.gateway.port)
        c.open(session="s4")
        for i in range(3):
            c.send_frame({"x": [float(i)]})
        c.sock.close()                  # mid-stream, no close op

    thread, box = in_thread(interact)
    finish(runtime, thread, box)
    run_until(runtime,
              lambda: not pipeline.streams
              and pipeline.gateway.session_count() == 0,
              timeout=30.0)
    assert not pipeline.streams, "disconnect leaked the stream"
    assert pipeline.gateway.session_count() == 0


def test_ws_malformed_data_and_window_clamp(runtime):
    """Review hardening: a malformed payload costs a ``rejected``
    reply (never the connection, never a window slot); a client-
    requested window is clamped to the policy's session_window
    ceiling."""
    pipeline = gateway_pipeline(runtime,
                                qos={"session_window": 4})

    def interact():
        with GatewayClient("127.0.0.1", pipeline.gateway.port) as c:
            opened = c.open(session="s6", window=1000000000)
            assert opened["window"] == 4, opened    # clamped
            c.send_frame({"x": [[1.0, 2.0], 3.0]})  # ragged mix
            reply = c.recv(timeout=10.0)
            # the connection survived: a good frame still works
            c.send_frame({"x": [2.0]})
            result = c.next_result()
            return reply, result

    thread, box = in_thread(interact)
    reply, result = finish(runtime, thread, box)
    assert reply["op"] in ("rejected", "result"), reply
    if reply["op"] == "rejected":
        assert reply["reason"] == "bad-data"
    assert result["ok"] and result["data"]["x"][0] == 12.0


def test_create_failure_after_bind_closes_the_gateway(runtime):
    """Review hardening: a create-time DefinitionError raised AFTER
    the gateway binds (qos parse, graph build) must not leak the
    listening socket serving a half-constructed pipeline."""
    from aiko_services_tpu.pipeline.definition import DefinitionError

    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()                       # freed for the doomed pipeline
    definition = {
        "version": 0, "name": "gw_broken", "runtime": "jax",
        "graph": ["(detect llm)"],
        "parameters": {"gateway": "on", "gateway_port": port,
                       "preflight": "off",
                       "qos": {"tenants": {"a": {"class": "gold"}}}},
        "elements": [stage("detect"), stage("llm")]}
    with pytest.raises(DefinitionError):
        Pipeline(definition, runtime=runtime)
    with pytest.raises(OSError):
        socket.create_connection(("127.0.0.1", port), timeout=2.0)


def test_ws_payload_bound_kills_oversized_frames():
    """Review hardening: an attacker-chosen 64-bit frame length (or
    endless continuation fragments) must die at the codec bound, not
    buffer into RAM."""
    import socket as socket_module
    import struct

    from aiko_services_tpu.gateway import ws

    a, b = socket_module.socketpair()
    try:
        # FIN text frame claiming an 8 GiB payload
        a.sendall(bytes([0x81, 127]) + struct.pack(">Q", 8 << 30))
        with pytest.raises(ws.WsClosed, match="bound"):
            ws.recv_frame(b)
    finally:
        a.close()
        b.close()
    a, b = socket_module.socketpair()
    try:
        chunk = b"x" * 1024
        # non-FIN text frame, then continuation fragments past the cap
        a.sendall(bytes([0x01, 126]) + struct.pack(">H", len(chunk))
                  + chunk)
        for _ in range(4):
            a.sendall(bytes([0x00, 126]) + struct.pack(">H", len(chunk))
                      + chunk)

        with pytest.raises(ws.WsClosed, match="bound"):
            ws.recv_message(b, max_payload=2048)
    finally:
        a.close()
        b.close()


def test_lazy_tenant_cap_bounds_cardinality():
    """Unauthenticated tenant names must not grow scheduler state
    without bound: past LAZY_TENANT_CAP, unknown names share the
    default entry."""
    from aiko_services_tpu.gateway.qos import (LAZY_TENANT_CAP,
                                               QosScheduler)
    qos = QosScheduler({"tenants": {"alice": {"budget": 8}}})
    for index in range(LAZY_TENANT_CAP + 50):
        qos.tenant(f"rando-{index}")
    # configured + cap (+ the shared default overflow entry)
    assert len(qos.tenants) <= 1 + LAZY_TENANT_CAP + 1
    overflow = qos.tenant("rando-way-past-the-cap")
    assert overflow.name == "default"
    assert qos.tenant("alice").budget == 8      # configured untouched


# -- HTTP + admission -------------------------------------------------------

def test_http_frame_request_response_and_rate_limit(runtime):
    """POST /v1/frames runs one frame request/response; the tenant's
    token bucket rejects the over-rate call with 429."""
    pipeline = gateway_pipeline(
        runtime,
        qos={"tenants": {"meter": {"rate": 0.5, "burst": 1}}})
    port = pipeline.gateway.port

    def post(payload):
        request = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/frames",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(request, timeout=30) as reply:
                return reply.status, json.loads(reply.read())
        except urllib.error.HTTPError as error:
            return error.code, json.loads(error.read())

    def interact():
        first = post({"tenant": "meter", "data": {"x": [2.0, 2.0]}})
        second = post({"tenant": "meter", "data": {"x": [2.0]}})
        health = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10).read())
        stats = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats", timeout=10).read())
        return first, second, health, stats

    thread, box = in_thread(interact)
    first, second, health, stats = finish(runtime, thread, box)
    status, body = first
    assert status == 200 and body["ok"]
    assert body["data"]["x"] == [12.0, 12.0]    # *2 then *3
    status, body = second
    assert status == 429 and body["reason"] == "rate"
    assert health["ok"] is True
    assert stats["qos"]["tenants"]["meter"]["rejected"] >= 1
    run_until(runtime, lambda: not pipeline.streams, timeout=30.0)


def test_ws_rate_limit_rejected_and_observability(runtime):
    """Over-rate WS frames get ``rejected`` (reason rate); admission
    and rejection both land on the metrics plane (labeled counters),
    the ring (gw_admit/gw_reject), and the telemetry rollup's tenant
    rows."""
    pipeline = gateway_pipeline(
        runtime,
        qos={"tenants": {"metered": {"rate": 1.0, "burst": 2,
                                     "class": "interactive"}}})

    def interact():
        with GatewayClient("127.0.0.1", pipeline.gateway.port) as c:
            c.open(session="s5", tenant="metered")
            for i in range(4):
                c.send_frame({"x": [1.0]})
            seen = {"result": 0, "rejected": 0}
            deadline = time.monotonic() + 30.0
            while sum(seen.values()) < 4 \
                    and time.monotonic() < deadline:
                message = c.recv(timeout=10.0)
                if message["op"] in seen:
                    seen[message["op"]] += 1
            return seen

    thread, box = in_thread(interact)
    seen = finish(runtime, thread, box)
    assert seen["result"] == 2 and seen["rejected"] == 2, seen
    text = pipeline.metrics_text()
    assert 'gateway_admits{cls="interactive",tenant="metered"}' in text
    assert 'gateway_rejects' in text and 'reason="rate"' in text
    assert "gateway_e2e_ms" in text
    assert 'qos_inflight{tenant="metered"}' in text
    events = {e[1] for e in pipeline.recorder.snapshot()}
    assert "gw_admit" in events and "gw_reject" in events
    rollup = pipeline.telemetry.rollup()
    assert rollup["tenants"]["metered"]["admitted"] == 2
    assert rollup["tenants"]["metered"]["rejected"] == 2
    assert "interactive" in rollup.get("gateway", {})


# -- load generator + overload fairness -------------------------------------

def test_loadgen_overload_sheds_batch_not_interactive(runtime):
    """2x overload through the REAL gateway: the interactive tenant
    (in budget) keeps 100% goodput while the over-budget batch tenant
    absorbs every shed -- the Vortex contract, measured by the same
    loadgen the bench drives."""
    pipeline = gateway_pipeline(
        runtime,
        qos={"classes": {"batch": {"device_inflight": 1}},
             "tenants": {"alice": {"class": "interactive",
                                   "budget": 32},
                         "bulk": {"class": "batch", "budget": 2}},
             "max_inflight": 8, "age_ms": 60000},
        busy_ms=15.0)
    # busy_ms=15 per stage bounds the pipeline near ~66 fps even with
    # every jit warm (suite order must not turn the overload into
    # headroom): ~105 fps offered is a genuine ~1.6x overload, with
    # the interactive tenant comfortably inside capacity.
    specs = [
        LoadSpec("alice", "interactive", rate=15.0, frames=30,
                 data={"x": [1.0] * 8}),
        LoadSpec("bulk", "batch", rate=90.0, frames=120,
                 data={"x": [1.0] * 8}),
    ]

    def interact():
        return run_loadgen("127.0.0.1", pipeline.gateway.port, specs)

    thread, box = in_thread(interact)
    report = finish(runtime, thread, box, timeout=180.0)
    assert report["errors"] == []
    alice = report["tenants"]["alice"]
    bulk = report["tenants"]["bulk"]
    assert alice["sent"] == 30 and bulk["sent"] == 120
    assert alice["ok"] == 30, alice      # interactive: zero loss
    assert alice["shed"] == 0
    assert bulk["shed"] >= 1, bulk       # batch absorbed the shedding
    stats = pipeline.qos_stats()
    assert stats["tenants"]["bulk"]["shed"] >= 1
    assert stats["tenants"]["alice"]["shed"] == 0
    classes = report["classes"]
    assert classes["interactive"]["p99_ms"] > 0
    assert classes["interactive"]["goodput_fps"] > 0
