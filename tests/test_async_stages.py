"""Async local stages: frames overlap stages (the framework's core
thesis -- dataflow over an asynchronous accelerator).

An ``is_async`` element submits its frame's work and the engine parks the
frame (the in-process twin of the remote park/forward/resume), so N
frames are in flight at once and steady-state throughput approaches
1/max(stage time) instead of 1/sum(stage times); a batching element
(LLM) sees requests from many in-flight frames and decodes them together.
"""

import json
import queue
import threading
import time
from collections import deque

import numpy as np

from conftest import run_until

from aiko_services_tpu.pipeline import (PipelineElement, StreamEvent,
                                        create_pipeline)

DELAY = 0.05          # per-stage injected service time (seconds)
FRAMES = 8


class SerialDelay(PipelineElement):
    """Async element serving one frame at a time, each taking ``delay``
    seconds on its own worker -- models an accelerator stage with a
    fixed service time.  Overlap across STAGES is the engine's job."""

    is_async = True

    def __init__(self, context):
        super().__init__(context)
        self._queue = deque()
        self._busy = False
        self._lock = threading.Lock()
        self.max_in_service = 0       # proves per-stage serialization

    def process_frame_start(self, stream, complete, value=None, **inputs):
        delay, _ = self.get_parameter("delay", DELAY)
        with self._lock:
            self._queue.append((complete, float(delay), value))
            if self._busy:
                return
            self._busy = True
        self._serve_next()

    def _serve_next(self):
        with self._lock:
            if not self._queue:
                self._busy = False
                return
            complete, delay, value = self._queue.popleft()

        def fire():
            complete(StreamEvent.OKAY, {"value": value})
            self._serve_next()

        threading.Timer(delay, fire).start()


class AsyncError(PipelineElement):
    is_async = True

    def process_frame_start(self, stream, complete, value=None, **inputs):
        threading.Timer(0.01, lambda: complete(
            StreamEvent.ERROR, {"diagnostic": "boom"})).start()


class NeverComplete(PipelineElement):
    """Async element that parks the frame and never calls complete --
    models a dead remote stage / wedged accelerator."""

    is_async = True

    def process_frame_start(self, stream, complete, value=None, **inputs):
        pass


class DoubleComplete(PipelineElement):
    is_async = True

    def process_frame_start(self, stream, complete, value=None, **inputs):
        complete(StreamEvent.OKAY, {"value": value})
        complete(StreamEvent.OKAY, {"value": "SECOND"})   # must be ignored


def _two_stage_definition(tmp_path, cls_b="SerialDelay",
                          params_b=None):
    definition = {
        "version": 0, "name": "async_pipe", "runtime": "jax",
        "graph": ["(a b)"],
        "elements": [
            {"name": "a",
             "input": [{"name": "value"}],
             "output": [{"name": "value"}],
             "deploy": {"local": {"module": "test_async_stages",
                                  "class_name": "SerialDelay"}}},
            {"name": "b",
             "input": [{"name": "value"}],
             "output": [{"name": "value"}],
             "parameters": params_b or {},
             "deploy": {"local": {"module": "test_async_stages",
                                  "class_name": cls_b}}},
        ]}
    path = tmp_path / "async.json"
    path.write_text(json.dumps(definition))
    return str(path)


def test_frames_overlap_stages(tmp_path, runtime):
    """Two serial stages of DELAY each: sync cost is FRAMES * 2 * DELAY;
    pipelined cost approaches (FRAMES + 1) * DELAY.  The midpoint
    separates the two regimes with margin on a loaded machine."""
    responses = queue.Queue()
    pipeline = create_pipeline(_two_stage_definition(tmp_path),
                               runtime=runtime)
    stream = pipeline.create_stream_local("s", queue_response=responses)

    start = time.perf_counter()
    for i in range(FRAMES):
        pipeline.create_frame_local(stream, {"value": i})
    assert run_until(runtime, lambda: responses.qsize() >= FRAMES,
                     timeout=20.0)
    elapsed = time.perf_counter() - start

    sync_floor = FRAMES * 2 * DELAY                  # 0.8 s
    pipelined = (FRAMES + 1) * DELAY                 # 0.45 s
    assert elapsed < (sync_floor + pipelined) / 2, (
        f"elapsed {elapsed:.3f}s: frames did not overlap stages "
        f"(serialized floor {sync_floor:.3f}s)")

    values = set()
    while not responses.empty():
        _, _, swag, metrics, okay, diagnostic = responses.get()
        assert okay, diagnostic
        values.add(swag["value"])
        # per-stage timing metric still recorded on the async path
        assert metrics["a_time"] >= DELAY * 0.5
    assert values == set(range(FRAMES))
    pipeline.stop()


def test_async_error_propagates(tmp_path, runtime):
    responses = queue.Queue()
    pipeline = create_pipeline(
        _two_stage_definition(tmp_path, cls_b="AsyncError"),
        runtime=runtime)
    stream = pipeline.create_stream_local("s", queue_response=responses)
    pipeline.create_frame_local(stream, {"value": 1})
    assert run_until(runtime, lambda: not responses.empty(), timeout=10.0)
    _, _, _, _, okay, diagnostic = responses.get()
    assert not okay
    assert "boom" in diagnostic
    pipeline.stop()


def test_double_complete_ignored(tmp_path, runtime):
    responses = queue.Queue()
    pipeline = create_pipeline(
        _two_stage_definition(tmp_path, cls_b="DoubleComplete"),
        runtime=runtime)
    stream = pipeline.create_stream_local("s", queue_response=responses)
    pipeline.create_frame_local(stream, {"value": 7})
    assert run_until(runtime, lambda: not responses.empty(), timeout=10.0)
    _, _, swag, _, okay, _ = responses.get()
    assert okay and swag["value"] == 7
    time.sleep(0.05)
    assert responses.empty()          # the second complete() went nowhere
    pipeline.stop()


def test_synchronous_parameter_forces_blocking_path(tmp_path, runtime):
    """``synchronous: true`` on an async-capable element runs the
    blocking process_frame -- SerialDelay has no sync path, so instead
    use the Detector, which implements both."""
    definition = {
        "version": 0, "name": "detect_sync", "runtime": "jax",
        "graph": ["(detect)"],
        "elements": [{
            "name": "detect",
            "input": [{"name": "image"}],
            "output": [{"name": "detections"}],
            "parameters": {"synchronous": True, "width": 4},
            "deploy": {"local": {
                "module": "aiko_services_tpu.elements.detect",
                "class_name": "Detector"}}}]}
    path = tmp_path / "detect.json"
    path.write_text(json.dumps(definition))
    responses = queue.Queue()
    pipeline = create_pipeline(str(path), runtime=runtime)
    stream = pipeline.create_stream_local("s", queue_response=responses)
    image = np.zeros((64, 64, 3), dtype=np.uint8)
    pipeline.create_frame_local(stream, {"image": image})
    assert run_until(runtime, lambda: not responses.empty(), timeout=60.0)
    _, _, swag, _, okay, diagnostic = responses.get()
    assert okay, diagnostic
    assert isinstance(swag["detections"], list)
    pipeline.stop()


def test_detector_async_matches_sync(tmp_path, runtime):
    """The async (parked) Detector path produces the same outputs as the
    blocking path."""
    definition = {
        "version": 0, "name": "detect_async", "runtime": "jax",
        "graph": ["(detect)"],
        "elements": [{
            "name": "detect",
            "input": [{"name": "image"}],
            "output": [{"name": "detections"}, {"name": "overlay"}],
            "parameters": {"width": 4},
            "deploy": {"local": {
                "module": "aiko_services_tpu.elements.detect",
                "class_name": "Detector"}}}]}
    path = tmp_path / "detect.json"
    path.write_text(json.dumps(definition))
    responses = queue.Queue()
    pipeline = create_pipeline(str(path), runtime=runtime)
    stream = pipeline.create_stream_local("s", queue_response=responses)
    image = (np.random.default_rng(0)
             .integers(0, 255, (64, 64, 3)).astype(np.uint8))
    pipeline.create_frame_local(stream, {"image": image})
    assert run_until(runtime, lambda: not responses.empty(), timeout=60.0)
    _, _, swag, _, okay, diagnostic = responses.get()
    assert okay, diagnostic

    element = pipeline.graph.get_node("detect").element
    event, sync_out = element.process_frame(stream, image=image)
    assert event == StreamEvent.OKAY
    assert swag["detections"] == sync_out["detections"]
    assert swag["overlay"] == sync_out["overlay"]
    pipeline.stop()


def test_detector_microbatches_burst(tmp_path, runtime):
    """A burst of parked frames dispatches as ONE batched detect (r5:
    elements/detect.py micro-batching), and each frame still gets ITS
    OWN row's outputs -- identical to the per-frame blocking path."""
    n_frames = 4
    definition = {
        "version": 0, "name": "detect_burst", "runtime": "jax",
        "graph": ["(detect)"],
        "elements": [{
            "name": "detect",
            "input": [{"name": "image"}],
            "output": [{"name": "detections"}, {"name": "overlay"}],
            "parameters": {"width": 4, "max_batch": 8},
            "deploy": {"local": {
                "module": "aiko_services_tpu.elements.detect",
                "class_name": "Detector"}}}]}
    path = tmp_path / "detect.json"
    path.write_text(json.dumps(definition))
    responses = queue.Queue()
    pipeline = create_pipeline(str(path), runtime=runtime)
    stream = pipeline.create_stream_local("s", queue_response=responses)
    rng = np.random.default_rng(0)
    images = [rng.integers(0, 255, (64, 64, 3)).astype(np.uint8)
              for _ in range(n_frames)]
    for image in images:
        pipeline.create_frame_local(stream, {"image": image})
    assert run_until(runtime, lambda: responses.qsize() >= n_frames,
                     timeout=120.0)

    element = pipeline.graph.get_node("detect").element
    dispatches = element.jit_cache.hits + element.jit_cache.misses
    assert dispatches < n_frames, (
        f"{dispatches} dispatches for {n_frames} frames: not batched")

    by_frame = {}
    while not responses.empty():
        _, frame_id, swag, _, okay, diagnostic = responses.get()
        assert okay, diagnostic
        by_frame[frame_id] = swag
    assert len(by_frame) == n_frames
    for frame_id, image in enumerate(images):
        _, sync_out = element.process_frame(stream, image=image)
        assert by_frame[frame_id]["detections"] \
            == sync_out["detections"]
        assert by_frame[frame_id]["overlay"] == sync_out["overlay"]
    pipeline.stop()


def test_detector_bad_frame_errors_only_its_group(tmp_path, runtime):
    """A malformed frame in a micro-batched burst must error ITSELF
    (its shape group / its stream -- a frame error destroys its stream
    by engine design) while other streams' frames in the SAME batched
    burst complete: a failed dispatch must never strand parked frames."""
    definition = {
        "version": 0, "name": "detect_bad", "runtime": "jax",
        "graph": ["(detect)"],
        "elements": [{
            "name": "detect",
            "input": [{"name": "image"}],
            "output": [{"name": "detections"}],
            "parameters": {"width": 4, "max_batch": 8},
            "deploy": {"local": {
                "module": "aiko_services_tpu.elements.detect",
                "class_name": "Detector"}}}]}
    path = tmp_path / "detect.json"
    path.write_text(json.dumps(definition))
    good_responses = queue.Queue()
    bad_responses = queue.Queue()
    pipeline = create_pipeline(str(path), runtime=runtime)
    good_stream = pipeline.create_stream_local(
        "good", queue_response=good_responses)
    bad_stream = pipeline.create_stream_local(
        "bad", queue_response=bad_responses)
    rng = np.random.default_rng(0)
    pipeline.create_frame_local(good_stream, {
        "image": rng.integers(0, 255, (64, 64, 3)).astype(np.uint8)})
    pipeline.create_frame_local(bad_stream, {   # no channel dim
        "image": rng.integers(0, 255, (64, 64)).astype(np.uint8)})
    pipeline.create_frame_local(good_stream, {
        "image": rng.integers(0, 255, (64, 64, 3)).astype(np.uint8)})
    assert run_until(
        runtime,
        lambda: good_responses.qsize() >= 2 and not bad_responses.empty(),
        timeout=120.0)
    *_, okay, diagnostic = bad_responses.get()
    assert not okay and "detect" in diagnostic    # dispatch error surfaced
    while not good_responses.empty():             # burst-mates completed
        _, _, swag, _, okay, diagnostic = good_responses.get()
        assert okay, diagnostic
        assert isinstance(swag["detections"], list)
    pipeline.stop()


def test_grace_lease_survives_parked_frames_then_reaps_idle(
        tmp_path, runtime):
    """The stream grace lease must NOT destroy a stream whose frame is
    parked at an async stage longer than the grace period (reference
    extends its lease per processed frame, ref pipeline.py:1425; here a
    parked frame has no per-frame tick, so expiry re-checks in-flight
    work) -- but a genuinely IDLE stream is still reaped."""
    responses = queue.Queue()
    pipeline = create_pipeline(
        _two_stage_definition(tmp_path, params_b={"delay": 1.2}),
        runtime=runtime)
    stream = pipeline.create_stream_local(
        "s", grace_time=0.4, queue_response=responses)
    pipeline.create_frame_local(stream, {"value": 1})
    # 1.2 s parked at stage b = three grace periods: previously the
    # lease destroyed the stream mid-flight and the frame vanished.
    assert run_until(runtime, lambda: not responses.empty(), timeout=15.0)
    _, _, swag, _, okay, diagnostic = responses.get()
    assert okay, diagnostic
    assert swag["value"] == 1
    assert "s" in pipeline.streams          # survived its parked frame

    # Now idle: the lease reaps it within ~2 grace periods.
    assert run_until(runtime, lambda: "s" not in pipeline.streams,
                     timeout=10.0), "idle stream was never reaped"
    pipeline.stop()


def test_grace_lease_stall_cap_reaps_wedged_frame(tmp_path, runtime):
    """A frame parked at a stage that NEVER completes must not revive
    the stream's grace lease forever: past the stall cap (10 grace
    periods) the stream is reaped, frames and all."""
    import importlib
    pipeline_mod = importlib.import_module(
        "aiko_services_tpu.pipeline.pipeline")
    responses = queue.Queue()
    pipeline = create_pipeline(
        _two_stage_definition(tmp_path, cls_b="NeverComplete"),
        runtime=runtime)
    stream = pipeline.create_stream_local(
        "s", grace_time=0.1, queue_response=responses)
    pipeline.create_frame_local(stream, {"value": 1})
    cap = 0.1 * pipeline_mod._STALL_REAP_FACTOR          # 1 s
    assert run_until(runtime, lambda: "s" not in pipeline.streams,
                     timeout=cap + 5.0), \
        "wedged stream was never reaped past the stall cap"
    pipeline.stop()


def test_llm_batches_across_frames(tmp_path, runtime):
    """Multiple in-flight frames' requests decode TOGETHER in the shared
    batcher (continuous batching across frames, not per-frame drains):
    total decode steps stay near one request's worth, far below the
    serialized sum."""
    n_frames, max_new = 4, 12
    definition = {
        "version": 0, "name": "llm_async", "runtime": "jax",
        "graph": ["(llm)"],
        "elements": [{
            "name": "llm",
            "input": [{"name": "text"}],
            "output": [{"name": "text"}],
            "parameters": {"max_new_tokens": max_new, "max_seq": 64},
            "deploy": {"local": {
                "module": "aiko_services_tpu.elements.llm",
                "class_name": "LLM"}}}]}
    path = tmp_path / "llm.json"
    path.write_text(json.dumps(definition))
    responses = queue.Queue()
    pipeline = create_pipeline(str(path), runtime=runtime)
    stream = pipeline.create_stream_local("s", queue_response=responses)
    for i in range(n_frames):
        pipeline.create_frame_local(stream, {"text": f"prompt {i}"})
    assert run_until(runtime, lambda: responses.qsize() >= n_frames,
                     timeout=120.0)
    texts = []
    while not responses.empty():
        _, _, swag, _, okay, diagnostic = responses.get()
        assert okay, diagnostic
        texts.append(swag["text"])
    assert len(texts) == n_frames

    batcher = pipeline.graph.get_node("llm").element._batcher
    serialized_steps = n_frames * max_new
    assert batcher.steps < serialized_steps * 0.6, (
        f"{batcher.steps} decode steps for {n_frames} frames x "
        f"{max_new} tokens: requests did not batch across frames")
    pipeline.stop()
