"""Fleet observability plane (ISSUE 19): door-to-decode tracing
through the real gateway, registrar-discovered metrics federation with
EXACT histogram merge + monotonic counters across death/adoption, and
per-tenant SLO error budgets.

Acceptance shapes:

- a WebSocket request through the gateway to a placed pipeline with a
  remote hop is ONE trace -- gateway spans, origin spans and remote
  spans under one trace_id, resolvable by ``explain_frame``;
- the SAME trace_id continues across a kill-mid-stream failover:
  the journal records it per frame, the adopter's replay re-ingests
  with it, and the client's post-failover result names it;
- a collector scraping >= 2 live processes merges histograms exactly
  (fleet quantile == the quantile of a hand-merged reference) and its
  counters never decrease across rolling restart or SIGKILL+adoption,
  with zero scrape errors.
"""

import json
import queue
import threading
import time

import pytest

from conftest import run_until

from aiko_services_tpu.gateway.client import GatewayClient
from aiko_services_tpu.gateway.qos import (SLO_FIRE_COOLDOWN_S,
                                           SloTracker, slo_spec_error)
from aiko_services_tpu.gateway.server import GatewayServer
from aiko_services_tpu.observability import LogHistogram
from aiko_services_tpu.observability.fleet import FleetCollector
from aiko_services_tpu.pipeline import DefinitionError, Pipeline
from aiko_services_tpu.pipeline.journal import load_journal
from aiko_services_tpu.services import Registrar

COMMON = "aiko_services_tpu.elements.common"


def element(name, cls, parameters=None, placement=None):
    definition = {"name": name, "input": [{"name": "x"}],
                  "output": [{"name": "x"}],
                  "deploy": {"local": {"module": COMMON,
                                       "class_name": cls}},
                  "parameters": parameters or {}}
    if placement:
        definition["placement"] = placement
    return definition


def remote(name, target):
    return {"name": name, "input": [{"name": "x"}],
            "output": [{"name": "x"}],
            "deploy": {"remote": {"name": target}}}


def stage(name, busy_ms=1.0, factor=2.0, devices=2):
    return element(name, "StageWork",
                   {"busy_ms": busy_ms, "factor": factor},
                   placement={"devices": devices})


def simple_pipeline(runtime, name, extra=None):
    parameters = dict(extra or {})
    return Pipeline({"version": 0, "name": name, "runtime": "jax",
                     "graph": ["(inc)"],
                     "parameters": parameters,
                     "elements": [element("inc", "Increment")]},
                    runtime=runtime)


def push_frames(runtime, pipeline, stream_id, n):
    responses = queue.Queue()
    pipeline.create_stream_local(stream_id, queue_response=responses)
    for _ in range(n):
        pipeline.process_frame_local({"x": 0}, stream_id=stream_id)
    assert run_until(runtime, lambda: responses.qsize() == n,
                     timeout=30.0)


def in_thread(target):
    box: dict = {}

    def body():
        try:
            box["value"] = target()
        except Exception as error:      # surfaced by the test
            box["error"] = error
    thread = threading.Thread(target=body, daemon=True)
    thread.start()
    return thread, box


def finish(runtime, thread, box, timeout=90.0):
    run_until(runtime, lambda: not thread.is_alive(), timeout=timeout)
    assert not thread.is_alive(), "client interaction hung"
    if "error" in box:
        raise box["error"]
    return box.get("value")


# -- SLO engine (jax-free units) --------------------------------------------

def test_slo_spec_error_vocabulary():
    assert slo_spec_error({}) is None
    assert slo_spec_error({"interactive": {"p99_ms": 100,
                                           "availability": 0.999,
                                           "window_s": 30}}) is None
    assert "dict" in slo_spec_error([1, 2])
    assert "dict" in slo_spec_error({"interactive": 5})
    assert "unknown" in slo_spec_error(
        {"interactive": {"p99": 100}})
    assert "declare" in slo_spec_error(
        {"interactive": {"window_s": 30}})
    assert "p99_ms" in slo_spec_error(
        {"interactive": {"p99_ms": 0}})
    assert "availability" in slo_spec_error(
        {"interactive": {"availability": 1.0}})
    assert "availability" in slo_spec_error(
        {"interactive": {"availability": 1.5}})
    assert "availability" in slo_spec_error(
        {"interactive": {"availability": 0.0}})


def test_slo_tracker_burn_and_debounce():
    tracker = SloTracker({"interactive": {"p99_ms": 10.0,
                                          "availability": 0.9,
                                          "window_s": 60.0}})
    now = 1000.0
    # In budget: fast, successful frames -> zero burn, nothing fires.
    for _ in range(50):
        tracker.observe("alice", "interactive", 2.0, True, now=now)
    assert tracker.fast_burns(now=now) == []
    burns = tracker.burn_rates(now=now)
    assert burns["alice"]["interactive"]["burn"] == 0.0
    # Untracked class: no objective, no samples, no crash.
    tracker.observe("alice", "batch", 500.0, False, now=now)
    assert "batch" not in burns.get("alice", {})

    # Burn: every frame over the latency objective -> latency burn
    # 100x (100% violations against the 1% budget a p99 implies).
    for _ in range(50):
        tracker.observe("bob", "interactive", 50.0, True, now=now)
    fired = tracker.fast_burns(now=now)
    assert ("bob", "interactive") in [(t, c) for t, c, _ in fired]
    burn = tracker.burn_rates(now=now)["bob"]["interactive"]
    assert burn["latency_burn"] == pytest.approx(100.0)
    # Debounced: an immediate re-check does not re-fire ...
    assert tracker.fast_burns(now=now + 1.0) == []
    # ... but after the cooldown a sustained burn fires again.
    tracker.observe("bob", "interactive", 50.0, True,
                    now=now + SLO_FIRE_COOLDOWN_S + 1.0)
    again = tracker.fast_burns(now=now + SLO_FIRE_COOLDOWN_S + 1.0)
    assert ("bob", "interactive") in [(t, c) for t, c, _ in again]
    assert tracker.fired == 2

    # Availability burn from latency-less bad events (rejects/sheds).
    for _ in range(20):
        tracker.observe("carol", "interactive", None, False, now=now)
    entry = tracker.burn_rates(now=now)["carol"]["interactive"]
    assert entry["availability_burn"] == pytest.approx(10.0)
    snapshot = tracker.snapshot(now=now)
    assert snapshot["objectives"]["interactive"]["p99_ms"] == 10.0
    assert "carol" in snapshot["tenants"]


def test_bad_slo_is_create_time_error_even_without_preflight(runtime):
    with pytest.raises(DefinitionError, match="availability"):
        Pipeline({"version": 0, "name": "badslo", "runtime": "jax",
                  "graph": ["(inc)"],
                  "parameters": {"preflight": "off",
                                 "slo": {"interactive":
                                         {"p99_ms": 50,
                                          "availability": 1.5}}},
                  "elements": [element("inc", "Increment")]},
                 runtime=runtime)
    assert "badslo" not in [getattr(s, "name", "") for s in
                            runtime.services()]


# -- door-to-decode tracing -------------------------------------------------

def test_ws_request_is_one_trace_gateway_origin_remote(runtime):
    """A WebSocket frame through the real gateway into a placed
    pipeline with a remote hop yields ONE trace: gateway spans (root +
    admit + pump), origin spans, and the remote pipeline's spans, all
    under the trace_id the client's result names."""
    Registrar(runtime=runtime, primary_search_timeout=0.05)
    # The remote hop receives the placed stage's ARRAY output, so the
    # back element must be array-capable (StageWork, not Increment).
    back = Pipeline(
        {"version": 0, "name": "back", "runtime": "jax",
         "graph": ["(inc)"],
         "parameters": {},
         "elements": [element("inc", "StageWork", {"busy_ms": 1.0})]},
        runtime=runtime)
    front = Pipeline(
        {"version": 0, "name": "front", "runtime": "jax",
         "graph": ["(work (fwd))"],
         "parameters": {"gateway": "on"},
         "elements": [stage("work", busy_ms=1.0),
                      remote("fwd", "back")]},
        runtime=runtime)
    fwd = front.graph.get_node("fwd").element
    run_until(runtime, lambda: fwd.remote_topic_path is not None,
              timeout=10.0)
    client = GatewayClient("127.0.0.1", front.gateway.port,
                           timeout=60.0)

    def interact():
        client.open(session="t1", tenant="alice")
        client.send_frame({"x": [1.0] * 4})
        message = client.next_result(timeout=60.0)
        client.close()
        return message

    thread, box = in_thread(interact)
    message = finish(runtime, thread, box)
    assert message["ok"], message
    trace_id = message.get("trace")
    assert trace_id, "gateway result carried no trace id"

    trace = front.telemetry.traces.get(str(trace_id))
    assert trace is not None
    spans = trace["spans"]
    assert {span["trace_id"] for span in spans} == {str(trace_id)}
    kinds = [span["kind"] for span in spans]
    names = {span["name"] for span in spans}
    processes = {span["process"] for span in spans}
    assert kinds.count("gateway") >= 3          # root + admit + pump
    assert {"gateway:admit", "gateway:pump"} <= names
    assert {"front", "back"} <= processes       # origin + remote hop
    # The gateway root is the trace root; the engine's spans hang
    # below it (the dispatched frame carried trace_id + parent).
    root = next(span for span in spans
                if span["kind"] == "gateway"
                and span["parent_id"] is None)
    frame_roots = [span for span in spans if span["kind"] == "frame"
                   and span["process"] == "front"]
    assert frame_roots and all(span["parent_id"] == root["span_id"]
                               for span in frame_roots)
    # explain_frame resolves the gateway-minted id end to end.
    explained = front.explain_frame(str(trace_id))
    assert explained is not None
    assert explained["trace_id"] == str(trace_id)
    front.stop()
    back.stop()


def test_trace_id_survives_kill_failover_replay(runtime, tmp_path):
    """The journal records each frame's trace_id; after SIGKILL (the
    in-process twin) + adoption, replayed frames continue their
    ORIGINAL trace -- the id the client's late result names matches
    the dead process's journal, and the adopter's spans join it."""
    Registrar(runtime=runtime, primary_search_timeout=0.05)

    def serving(name, busy_ms):
        return Pipeline(
            {"version": 0, "name": name, "runtime": "jax",
             "graph": ["(work finish)"],
             "parameters": {"journal": "on",
                            "journal_dir": str(tmp_path)},
             "elements": [stage("work", busy_ms),
                          stage("finish", busy_ms, factor=3.0)]},
            runtime=runtime)

    p1 = serving("srv1", busy_ms=120.0)
    gateway = GatewayServer(runtime=runtime)
    run_until(runtime, lambda: len(gateway._peers) == 1)
    p2 = serving("srv2", busy_ms=5.0)
    run_until(runtime, lambda: len(gateway._peers) == 2)

    client = GatewayClient("127.0.0.1", gateway.port, timeout=90.0)
    n_frames = 5

    def phase_send():
        client.open(session="s1", tenant="t1")
        for index in range(n_frames):
            client.send_frame({"x": [float(index + 1)] * 4})
        return client.next_result()     # at least one from srv1

    thread, box = in_thread(phase_send)
    first = finish(runtime, thread, box)
    assert first["frame"] == 0 and first["ok"]
    assert first.get("trace"), "pre-kill result carried no trace id"

    # The dead-to-be journal knows each ingested frame's trace id.
    entry = load_journal(tmp_path / "srv1.journal").streams["gw/s1"]
    journal_tids = {frame_id: mirror.get("tid")
                    for frame_id, mirror in entry.frames.items()}
    assert all(journal_tids.get(frame_id) for frame_id
               in range(1, n_frames) if frame_id in journal_tids), \
        f"journal missing trace ids: {journal_tids}"

    p1.kill()                           # unclean death, mid-stream
    run_until(runtime, lambda: gateway.failovers == 1, timeout=10.0)
    run_until(runtime, lambda: p2.share["streams_adopted"] == 1,
              timeout=10.0)

    def phase_recv():
        results = [client.next_result() for _ in range(n_frames - 1)]
        client.close()
        return results

    thread, box = in_thread(phase_recv)
    rest = finish(runtime, thread, box)
    results = [first] + rest
    assert [r["frame"] for r in results] == list(range(n_frames))
    assert p2.share["frames_journal_replayed"] >= 1
    for result in rest:
        frame_id = result["frame"]
        if frame_id not in journal_tids:
            continue                    # delivered before the kill
        # Same id across the process boundary: journal == result.
        assert result.get("trace") == journal_tids[frame_id], \
            f"frame {frame_id}: trace id changed across failover"
    # The adopter's buffer holds the original trace with ITS spans.
    replayed_tid = next(journal_tids[r["frame"]] for r in rest
                        if r["frame"] in journal_tids)
    adopted = p2.telemetry.traces.get(replayed_tid)
    assert adopted is not None, \
        "adopter holds no spans for the replayed frame's trace"
    assert {span["process"] for span in adopted["spans"]} == {"srv2"}
    # The standalone door holds the WHOLE trace: its own gateway
    # spans plus the adopter's wire-returned spans, one id.
    own = gateway._own_traces.get(replayed_tid)
    assert own is not None
    kinds = {span["kind"] for span in own["spans"]}
    assert "gateway" in kinds
    assert "srv2" in {span["process"] for span in own["spans"]}
    gateway.stop()
    p2.stop()


# -- fleet federation -------------------------------------------------------

def test_fleet_merges_two_processes_exactly(runtime):
    """Two live pipelines with real scrape endpoints: the collector's
    merged histogram equals a hand-merged reference (same fixed bucket
    edges -> merge is addition, quantiles agree EXACTLY), and the
    exposition carries per-member rows plus aggregate rows."""
    Registrar(runtime=runtime, primary_search_timeout=0.05)
    p1 = simple_pipeline(runtime, "m1", extra={"metrics_port": 0})
    p2 = simple_pipeline(runtime, "m2", extra={"metrics_port": 0})
    assert p1.metrics_server is not None
    assert p1.share["metrics_port"] == p1.metrics_server.port

    collector = FleetCollector(runtime=runtime, scrape_ms=0)
    collector.start()
    run_until(runtime,
              lambda: len(collector.members_snapshot()) == 2,
              timeout=10.0)

    push_frames(runtime, p1, "s1", 6)
    push_frames(runtime, p2, "s2", 9)
    assert collector.scrape_once() == 0

    reference = LogHistogram()
    for pipeline in (p1, p2):
        state = next(
            entry for entry
            in pipeline.telemetry.registry.state()["histograms"]
            if entry["name"] == "frame_latency_ms"
            and not entry["labels"])
        reference.merge_state(state)
    merged = collector.merged_histogram("frame_latency_ms")
    assert merged.count == reference.count == 15
    for q in (0.5, 0.9, 0.99):
        assert merged.quantile(q, windowed=False) == \
            reference.quantile(q, windowed=False)
    assert collector.counter_value("frames_total",
                                   {"status": "ok"}) == 15.0

    text = collector.render_fleet_text()
    assert 'aiko_frame_latency_ms{pipeline="m1",quantile="0.99"}' \
        in text
    assert 'aiko_frame_latency_ms{quantile="0.99"}' in text  # merged
    assert 'aiko_frames_total{status="ok"} 15' in text
    collector.stop()
    p1.stop()
    p2.stop()


def test_fleet_counters_monotonic_across_churn(runtime, tmp_path):
    """Rolling restart and SIGKILL+adoption must never make a fleet
    counter decrease, and a scrape sweep over live members never
    errors: death is membership (LWT retire banks the incarnation),
    not a scrape failure."""
    Registrar(runtime=runtime, primary_search_timeout=0.05)

    def member(name):
        return simple_pipeline(
            runtime, name,
            extra={"metrics_port": 0, "journal": "on",
                   "journal_dir": str(tmp_path)})

    p1 = member("c1")
    p2 = member("c2")
    collector = FleetCollector(runtime=runtime, scrape_ms=0)
    collector.start()
    run_until(runtime,
              lambda: len(collector.members_snapshot()) == 2,
              timeout=10.0)

    push_frames(runtime, p1, "s1", 4)
    push_frames(runtime, p2, "s2", 4)
    assert collector.scrape_once() == 0
    total = collector.counter_value("frames_total", {"status": "ok"})
    assert total == 8.0

    # Rolling restart: drain c1, recreate the SAME name, fresh counts.
    p1.drain()
    run_until(runtime, lambda: p1.share.get("drained"), timeout=30.0)
    run_until(runtime,
              lambda: not any(row["alive"] and row["name"] == "c1"
                              for row in collector.members_snapshot()),
              timeout=10.0)
    p1b = member("c1")
    run_until(runtime,
              lambda: any(row["alive"] and row["name"] == "c1"
                          for row in collector.members_snapshot()),
              timeout=10.0)
    push_frames(runtime, p1b, "s1b", 3)
    assert collector.scrape_once() == 0
    after_roll = collector.counter_value("frames_total",
                                         {"status": "ok"})
    # Banked 4 (dead incarnation) + fresh 3 + c2's 4: never backwards.
    assert after_roll == 11.0
    assert after_roll >= total

    # SIGKILL twin: the dead member retires, totals stay banked.
    p2.kill()
    run_until(runtime,
              lambda: not any(row["alive"] and row["name"] == "c2"
                              for row in collector.members_snapshot()),
              timeout=10.0)
    assert collector.scrape_once() == 0
    after_kill = collector.counter_value("frames_total",
                                         {"status": "ok"})
    assert after_kill == after_roll     # its frames happened
    rows = collector.members_snapshot()
    assert sum(row["errors"] for row in rows) == 0
    assert collector.registry.state()["counters"] == [] or all(
        entry["name"] != "fleet_scrape_errors"
        for entry in collector.registry.state()["counters"])
    collector.stop()
    p1b.stop()


def test_fleet_slo_and_trace_views(runtime):
    """The in-gateway deployment: ``fleet: on`` inside a gateway
    pipeline serves /fleet, /fleet/slo and /fleet/traces/<id> over the
    door's own port, with the local pipeline scraped in-process."""
    import urllib.request

    Registrar(runtime=runtime, primary_search_timeout=0.05)
    pipeline = Pipeline(
        {"version": 0, "name": "fgw", "runtime": "jax",
         "graph": ["(inc)"],
         "parameters": {"gateway": "on", "fleet": "on",
                        "fleet_scrape_ms": 0,
                        "slo": {"interactive":
                                {"p99_ms": 0.001,
                                 "availability": 0.999}}},
         "elements": [element("inc", "Increment")]},
        runtime=runtime)
    port = pipeline.gateway.port
    client = GatewayClient("127.0.0.1", port, timeout=60.0)

    def interact():
        client.open(session="sv", tenant="alice",
                    qos_class="interactive")
        client.send_frame({"x": 5})      # scalar: the graph is Increment
        message = client.next_result(timeout=60.0)
        client.close()
        return message

    thread, box = in_thread(interact)
    message = finish(runtime, thread, box)
    assert message["ok"], message
    trace_id = str(message["trace"])
    pipeline.fleet_collector.scrape_once()

    def get(path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10.0) as r:
            return r.read().decode()

    fleet_text = get("/fleet")
    assert 'pipeline="fgw"' in fleet_text
    assert "aiko_fleet_members" in fleet_text
    slo = json.loads(get("/fleet/slo"))
    # The 1 us objective makes the single delivered frame a violation:
    # the burn is visible fleet-wide.
    assert slo["tenants"]["alice"]["interactive"]["burn"] > 1.0
    # The share refresh rides post_self -> the pipeline's event loop.
    assert run_until(runtime,
                     lambda: pipeline.share.get("slo_burn"),
                     timeout=10.0), \
        "slo burn missing from the share dict"
    assert pipeline.share["slo_burn"]["alice"]["interactive"] > 1.0
    trace = json.loads(get(f"/fleet/traces/{trace_id}"))
    assert trace["trace_id"] == trace_id
    kinds = {span["kind"] for span in trace["spans"]}
    assert "gateway" in kinds and len(trace["spans"]) >= 4
    pipeline.stop()
