"""Method-trace interceptor (reference main/proxy.py:36-75
ProxyAllMethods + proxy_trace)."""

import logging

import pytest

from aiko_services_tpu.utils import record_calls, trace_methods


class Example:
    def __init__(self):
        self.state = 0

    def bump(self, amount, scale=1):
        self.state += amount * scale
        return self.state

    def fail(self):
        raise ValueError("boom")

    def _private(self):
        return "untraced"


def test_trace_records_calls_and_shares_state():
    calls = []
    target = Example()
    traced = trace_methods(target, interceptor=record_calls(calls))
    assert traced.bump(2, scale=3) == 6
    assert traced.bump(1) == 7
    assert target.state == 7                 # same object, not a copy
    assert calls == [("bump", (2,), {"scale": 3}, 6),
                     ("bump", (1,), {}, 7)]
    # non-callables and _private pass through unwrapped
    assert traced.state == 7
    assert traced._private() == "untraced"
    assert calls[-1][0] == "bump"            # _private not recorded


def test_default_interceptor_logs_enter_exit_and_errors():
    # The framework logger does not propagate (it has its own console/
    # fabric handlers), so capture with a handler attached directly.
    records = []
    handler = logging.Handler()
    handler.emit = records.append
    logger = logging.getLogger("aiko.trace")
    logger.addHandler(handler)
    old_level = logger.level
    logger.setLevel(logging.DEBUG)
    traced = trace_methods(Example(), name="ex")
    try:
        traced.bump(1)
        with pytest.raises(ValueError, match="boom"):
            traced.fail()
    finally:
        logger.removeHandler(handler)
        logger.setLevel(old_level)
    messages = " ".join(record.getMessage() for record in records)
    assert "enter ex.bump" in messages
    assert "exit  ex.bump" in messages
    assert "error ex.fail" in messages       # exception still propagates


def test_trace_setattr_writes_through():
    target = Example()
    traced = trace_methods(target)
    traced.state = 42
    assert target.state == 42
