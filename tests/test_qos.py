"""Unified QoS admission (ISSUE 12): ONE QosScheduler authority
consulted by all four former admission planes -- DeviceWindow pacing,
StageScheduler credits, ReplicaGroup slot pick, batcher admission --
plus promotion near deadline, over-budget-first shedding under 2x
overload, and bounded wait for the lowest class."""

import queue
import time
import types

import numpy as np
import pytest

from conftest import run_until

from aiko_services_tpu.gateway.qos import (QosScheduler, TokenBucket,
                                           qos_spec_error)
from aiko_services_tpu.models.batching import ContinuousBatcher, \
    MicroBatcher, Request
from aiko_services_tpu.pipeline import Pipeline
from aiko_services_tpu.pipeline.stages import ReplicaGroup, StageScheduler

COMMON = "aiko_services_tpu.elements.common"


def frame_stub(qos_class="standard", seq=0, deadline=None,
               wait_start=None, tenant="default"):
    return types.SimpleNamespace(qos_class=qos_class, qos_seq=seq,
                                 deadline=deadline,
                                 qos_wait_start=wait_start,
                                 qos_promoted=False, tenant=tenant)


# -- units: scheduler vocabulary --------------------------------------------

def test_token_bucket_rate_and_burst():
    bucket = TokenBucket(rate=10.0, burst=2.0)
    now = time.monotonic()
    assert bucket.take(now) and bucket.take(now)     # burst of 2
    assert not bucket.take(now)                      # drained
    assert bucket.take(now + 0.11)                   # 1 token refilled
    unlimited = TokenBucket(rate=0.0)
    assert all(unlimited.take() for _ in range(100))


def test_spec_validation_rejects_malformed_blocks():
    assert qos_spec_error({}) is None
    assert qos_spec_error({"tenants": {"a": {"rate": 5}}}) is None
    assert "unknown keys" in qos_spec_error({"priorities": {}})
    assert "class" in qos_spec_error(
        {"tenants": {"a": {"class": "gold"}}})
    assert "weight" in qos_spec_error(
        {"classes": {"interactive": {"weight": -1}}})
    assert "not a number" in qos_spec_error({"max_inflight": "many"})
    assert "unparseable" in qos_spec_error("{nope")
    with pytest.raises(ValueError):
        QosScheduler({"tenants": {"a": {"class": "gold"}}})
    assert QosScheduler.parse(None) is None
    assert QosScheduler.parse({}) is None


def test_class_ranks_follow_weights():
    qos = QosScheduler({"classes": {"realtime": {"weight": 100}}})
    assert qos.class_rank("realtime") == 0
    assert qos.class_rank("interactive") == 1
    assert qos.class_rank("batch") == 3
    assert qos.class_rank("unknown") == qos.class_rank("standard")


def test_rank_promotion_near_deadline_counts_once():
    qos = QosScheduler({"promote_ms": 50, "age_ms": 0})
    now = time.monotonic()
    batch = frame_stub("batch", seq=7, deadline=now + 0.02)
    rank, seq = qos.rank_frame(batch, now)
    assert (rank, seq) == (0, 7)            # promoted to the top class
    assert batch.qos_promoted and qos.promotions == 1
    qos.rank_frame(batch, now)
    assert qos.promotions == 1              # counted once per frame
    far = frame_stub("batch", seq=8, deadline=now + 10.0)
    assert qos.rank_frame(far, now)[0] == qos.class_rank("batch")


def test_rank_aging_bounds_lowest_class_wait():
    qos = QosScheduler({"age_ms": 100, "promote_ms": 0})
    now = time.monotonic()
    fresh = frame_stub("batch", seq=2, wait_start=now)
    waited = frame_stub("batch", seq=1, wait_start=now - 0.25)
    assert qos.rank_frame(fresh, now)[0] == qos.class_rank("batch")
    assert qos.rank_frame(waited, now)[0] == 0   # two steps up


def test_shed_key_over_budget_tenant_first_then_class_then_oldest():
    qos = QosScheduler({"tenants": {
        "hog": {"budget": 1}, "polite": {"budget": 8}}})
    for _ in range(3):
        qos.frame_started("hog")
    qos.frame_started("polite")
    hog = frame_stub("interactive", seq=1, tenant="hog")
    polite_batch = frame_stub("batch", seq=2, tenant="polite")
    # over-budget beats class: the hog's INTERACTIVE frame sheds
    # before an in-budget tenant's batch frame.
    assert qos.shed_key(hog) > qos.shed_key(polite_batch)
    older = frame_stub("batch", seq=3, tenant="polite")
    newer = frame_stub("batch", seq=9, tenant="polite")
    assert qos.shed_key(older) > qos.shed_key(newer)   # oldest first


def test_device_limit_per_class():
    qos = QosScheduler({"classes": {"batch": {"device_inflight": 1}}})
    assert qos.device_limit("batch", 3) == 1      # plane 1: capped
    assert qos.device_limit("interactive", 3) == 3
    assert qos.device_limit("batch", 0) == 1      # pacing off -> cap


def test_tenant_lazily_resolves_default_block():
    qos = QosScheduler({"default_tenant": {"budget": 2,
                                           "class": "batch"}})
    entry = qos.tenant("never-seen")
    assert entry.budget == 2 and entry.default_class == "batch"
    assert qos.resolve_class(None, "never-seen") == "batch"


# -- units: the four planes -------------------------------------------------

def test_replica_pick_least_loaded_probes_canaries_first():
    group = ReplicaGroup("s", 3, depth=2)
    group.admit(group.pick())               # rr: slot 0
    group.admit(group.pick())               # rr: slot 1
    assert group.pick(least_loaded=True) == 2
    group.active = [2, 1, 2]
    assert group.pick(least_loaded=True) == 1
    # a canary-READY half-open slot is probed before any live slot:
    # under pure latency-sensitive traffic the rebuilt replica must
    # not stay half-open (N-1 capacity) until a saturation burst.
    group.fail(0)
    group.rebuild(3, half_open=[0])
    group.active = [0, 1, 1]
    assert group.pick(least_loaded=True) == 0
    group.admit(0)                          # canary in flight now
    assert group.pick(least_loaded=True) == 1   # back to least-loaded


def test_resolve_class_consistent_before_lazy_entry_exists():
    qos = QosScheduler({"default_tenant": {"class": "interactive"}})
    # FIRST resolution (no lazy entry yet) must match the second
    first = qos.resolve_class(None, "bob")
    qos.tenant("bob")
    assert first == qos.resolve_class(None, "bob") == "interactive"


def test_stage_scheduler_pops_best_ranked_waiter():
    qos = QosScheduler({"age_ms": 0, "promote_ms": 0})
    scheduler = StageScheduler(["llm"], depth=1, qos=qos)
    assert scheduler.try_admit("llm")
    for seq, cls in enumerate(["batch", "batch", "interactive"]):
        scheduler.enqueue("llm",
                          ["s", seq, "llm", True,
                           frame_stub(cls, seq=seq)])
    waiter = scheduler.release("llm")       # release pops next waiter
    assert waiter[1] == 2                   # interactive overtakes
    scheduler.cancel_reservation("llm")
    assert scheduler.try_admit("llm")       # the popped token admits
    waiter = scheduler.release("llm")
    assert waiter[1] == 0                   # same class: FIFO by seq


def test_stage_credit_promotion_fires_on_promote_once():
    """ISSUE 18 satellite: a near-deadline batch frame promotes AT THE
    STAGE-CREDIT SEAM -- `_pop_ranked` lifts it over a standard frame
    queued ahead of it and fires ``on_promote`` exactly once (the
    callback Pipeline wires into ``share['qos_promotions']``), so the
    counter the gateway bench reports is reachable deterministically."""
    qos = QosScheduler({"promote_ms": 50, "age_ms": 0})
    promoted = []
    scheduler = StageScheduler(
        ["llm"], depth=1, qos=qos,
        on_promote=lambda sid, frame: promoted.append((sid, frame)))
    ahead = frame_stub("standard", seq=1)
    urgent = frame_stub("batch", seq=9,
                        deadline=time.monotonic() + 0.02)
    scheduler.enqueue("llm", ["s-ahead", 1, "llm", True, ahead])
    scheduler.enqueue("llm", ["s-urgent", 9, "llm", True, urgent])
    token = scheduler.next_waiter("llm")
    # batch (rank 3) promoted to rank 0 beats standard (rank 2)
    assert token[0] == "s-urgent"
    assert urgent.qos_promoted
    assert promoted == [("s-urgent", urgent)]
    assert qos.promotions == 1
    # the promoted frame requeues (stolen credit): re-ranking it must
    # NOT fire the callback or bump the counter a second time
    scheduler.cancel_reservation("llm")
    scheduler.enqueue("llm", token, front=True)
    again = scheduler.next_waiter("llm")
    assert again[0] == "s-urgent"
    assert len(promoted) == 1 and qos.promotions == 1


def test_stage_scheduler_fifo_without_qos():
    scheduler = StageScheduler(["llm"], depth=1)
    assert scheduler.try_admit("llm")
    for seq, cls in enumerate(["batch", "interactive"]):
        scheduler.enqueue("llm",
                          ["s", seq, "llm", True,
                           frame_stub(cls, seq=seq)])
    waiter = scheduler.release("llm")
    assert waiter[1] == 0                   # strict FIFO, no qos


def test_continuous_batcher_admits_best_rank():
    batcher = ContinuousBatcher.__new__(ContinuousBatcher)
    a = Request("a", [1], qos_rank=2)
    b = Request("b", [1], qos_rank=0)
    c = Request("c", [1], qos_rank=2)
    batcher.pending = [a, b, c]
    assert batcher._next_pending() is b     # plane 4: rank first
    assert batcher._next_pending() is a     # then queue order
    assert batcher._next_pending() is c


def test_microbatcher_dispatches_best_ranked_group_first():
    order = []

    def run(context, key, payloads):
        order.append(key)
        return payloads

    def finish(context, key, entries, result):
        for complete, payload in entries:
            complete("ok", {"x": payload})

    batcher = MicroBatcher(run=run, finish=finish,
                           context=lambda: None,
                           schedule_flush=lambda fn: None)
    done = []
    batcher.submit("batch", 1, lambda *a: done.append(a), rank=2)
    batcher.submit("interactive", 2, lambda *a: done.append(a), rank=0)
    batcher.flush()
    batcher.stop()
    deadline = time.monotonic() + 5.0
    while len(done) < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert order == ["interactive", "batch"]


# -- integration: the engine honors one authority ---------------------------

def element(name, cls, inputs, outputs, parameters=None, placement=None,
            module=COMMON):
    definition = {"name": name,
                  "input": [{"name": n} for n in inputs],
                  "output": [{"name": n} for n in outputs],
                  "deploy": {"local": {"module": module,
                                       "class_name": cls}},
                  "parameters": parameters or {}}
    if placement:
        definition["placement"] = placement
    return definition


def qos_two_stage(qos, busy_ms=25.0, extra=None):
    parameters = {"qos": qos, "stage_inflight": 1}
    parameters.update(extra or {})
    return {
        "version": 0, "name": "p_qos", "runtime": "jax",
        "graph": ["(detect llm)"],
        "parameters": parameters,
        "elements": [
            element("detect", "StageWork", ["x"], ["x"],
                    {"busy_ms": busy_ms, "factor": 2.0}, {"devices": 4}),
            element("llm", "StageWork", ["x"], ["x"],
                    {"busy_ms": busy_ms, "factor": 3.0}, {"devices": 4}),
        ]}


def pump(pipeline, stream_id, n, responses, parameters=None):
    for i in range(n):
        pipeline.process_frame_local(
            {"x": np.full((8, 8), float(i + 1), np.float32)},
            stream_id=stream_id, queue_response=responses)


def drain(runtime, responses, n, timeout=120.0):
    collected = []

    def drained():
        while not responses.empty():
            collected.append(responses.get())
        return len(collected) >= n
    run_until(runtime, drained, timeout=timeout)
    return collected


def test_interactive_overtakes_queued_batch_at_every_seam(runtime):
    """THE acceptance invariant: with one QosScheduler, an
    interactive-class frame admitted after a queue of batch frames
    overtakes them at the stage-credit seam (ring ``admit`` events
    prove the admission order) while per-stream delivery stays in
    ingest order."""
    pipeline = Pipeline(qos_two_stage(
        {"classes": {"batch": {"device_inflight": 1}},
         "age_ms": 60000, "promote_ms": 0}), runtime=runtime)
    batch_q: queue.Queue = queue.Queue()
    inter_q: queue.Queue = queue.Queue()
    pipeline.create_stream_local("b", {"qos_class": "batch"},
                                 queue_response=batch_q)
    pipeline.create_stream_local("i", {"qos_class": "interactive"},
                                 queue_response=inter_q)
    pump(pipeline, "b", 6, batch_q)
    pump(pipeline, "i", 2, inter_q)
    batch_rows = drain(runtime, batch_q, 6)
    inter_rows = drain(runtime, inter_q, 2)
    assert len(batch_rows) == 6 and len(inter_rows) == 2
    for *_, okay, diagnostic in batch_rows + inter_rows:
        assert okay, diagnostic
    # per-stream in-order delivery holds on both streams
    assert [r[1] for r in batch_rows] == sorted(
        r[1] for r in batch_rows)
    assert [r[1] for r in inter_rows] == sorted(
        r[1] for r in inter_rows)
    # admission order at the placed stages: interactive frames admit
    # before batch frames that were QUEUED ahead of them.
    admits = [(e[2], e[3], e[4]) for e in pipeline.recorder.snapshot()
              if e[1] == "admit"]
    detect_admits = [(s, f) for s, f, stage in admits
                     if stage == "detect"]
    first_inter = detect_admits.index(("i", 0))
    batch_after = [entry for entry in detect_admits[first_inter:]
                   if entry[0] == "b"]
    assert len(batch_after) >= 2, (
        f"interactive never overtook queued batch frames: "
        f"{detect_admits}")
    # the same authority capped batch's dispatch window (plane 1)
    assert pipeline._device_limit(pipeline.streams["b"]) == 1
    assert pipeline._device_limit(pipeline.streams["i"]) == 3


def test_promotion_near_deadline_overtakes_and_is_recorded(runtime):
    """A batch frame close to its deadline promotes to rank 0 at the
    waiter pop: counted once (share + counter + ring event)."""
    pipeline = Pipeline(qos_two_stage(
        {"promote_ms": 60000, "age_ms": 0}), runtime=runtime)
    std_q: queue.Queue = queue.Queue()
    ddl_q: queue.Queue = queue.Queue()
    pipeline.create_stream_local("std", {"qos_class": "standard"},
                                 queue_response=std_q)
    pipeline.create_stream_local(
        "ddl", {"qos_class": "batch", "frame_deadline_ms": 30000},
        queue_response=ddl_q)
    pump(pipeline, "std", 5, std_q)
    pump(pipeline, "ddl", 2, ddl_q)
    std_rows = drain(runtime, std_q, 5)
    ddl_rows = drain(runtime, ddl_q, 2)
    for *_, okay, diagnostic in std_rows + ddl_rows:
        assert okay, diagnostic
    assert pipeline.share["qos_promotions"] >= 1
    promotes = [e for e in pipeline.recorder.snapshot()
                if e[1] == "gw_promote"]
    assert promotes and promotes[0][2] == "ddl"
    # promoted batch frames overtook queued standard frames
    admits = [(e[2], e[3]) for e in pipeline.recorder.snapshot()
              if e[1] == "admit" and e[4] == "detect"]
    first_ddl = admits.index(("ddl", 0))
    assert any(entry[0] == "std" for entry in admits[first_ddl:]), \
        f"promotion never overtook: {admits}"


def test_overload_sheds_over_budget_tenant_first(runtime):
    """Under ~2x overload (max_inflight), the over-budget tenant's
    frames shed FIRST: the in-budget tenant completes everything."""
    pipeline = Pipeline(qos_two_stage(
        {"tenants": {"hog": {"budget": 2, "class": "batch"},
                     "polite": {"budget": 16, "class": "batch"}},
         "max_inflight": 6, "age_ms": 60000, "promote_ms": 0},
        busy_ms=30.0), runtime=runtime)
    hog_q: queue.Queue = queue.Queue()
    polite_q: queue.Queue = queue.Queue()
    pipeline.create_stream_local("hog", {"tenant": "hog"},
                                 queue_response=hog_q)
    pipeline.create_stream_local("polite", {"tenant": "polite"},
                                 queue_response=polite_q)
    pump(pipeline, "hog", 8, hog_q)
    pump(pipeline, "polite", 4, polite_q)
    hog_rows = drain(runtime, hog_q, 8)
    polite_rows = drain(runtime, polite_q, 4)
    assert len(hog_rows) == 8 and len(polite_rows) == 4
    polite_failures = [d for *_, okay, d in polite_rows if not okay]
    assert polite_failures == [], polite_failures
    hog_shed = sum(1 for *_, okay, d in hog_rows
                   if not okay and "shed" in d)
    assert hog_shed >= 1, "over-budget tenant was never shed"
    stats = pipeline.qos_stats()
    assert stats["tenants"]["hog"]["shed"] >= 1
    assert stats["tenants"].get("polite", {}).get("shed", 0) == 0
    assert pipeline.share["qos_sheds"] == pipeline._qos_sheds


def test_lowest_class_is_not_starved_bounded_wait(runtime):
    """Aging: under a steady stream of interactive frames, a lone
    batch frame still completes (age_ms lifts its rank step by
    step)."""
    pipeline = Pipeline(qos_two_stage(
        {"age_ms": 50, "promote_ms": 0}, busy_ms=15.0), runtime=runtime)
    inter_q: queue.Queue = queue.Queue()
    batch_q: queue.Queue = queue.Queue()
    pipeline.create_stream_local("i", {"qos_class": "interactive"},
                                 queue_response=inter_q)
    pipeline.create_stream_local("b", {"qos_class": "batch"},
                                 queue_response=batch_q)
    pump(pipeline, "i", 4, inter_q)
    pump(pipeline, "b", 1, batch_q)
    pump(pipeline, "i", 8, inter_q)     # keep the pressure on
    batch_rows = drain(runtime, batch_q, 1)
    assert len(batch_rows) == 1 and batch_rows[0][4], \
        "batch frame starved"
    drain(runtime, inter_q, 12)


def test_malformed_qos_block_fails_at_create(runtime):
    """Create-time validation (and the preflight-off escape hatch is
    closed): a typo'd tenant block raises DefinitionError."""
    from aiko_services_tpu.pipeline.definition import DefinitionError
    definition = qos_two_stage(
        {"tenants": {"a": {"class": "gold"}}})
    definition["parameters"]["preflight"] = "off"
    with pytest.raises(DefinitionError, match="qos"):
        Pipeline(definition, runtime=runtime)


def test_qos_off_keeps_legacy_behavior(runtime):
    """No qos block: scheduler absent, seams run exactly as before."""
    definition = qos_two_stage({})
    del definition["parameters"]["qos"]
    pipeline = Pipeline(definition, runtime=runtime)
    assert pipeline.qos is None
    assert pipeline.qos_stats() == {"enabled": False}
    responses: queue.Queue = queue.Queue()
    pipeline.create_stream_local("s", {}, queue_response=responses)
    pump(pipeline, "s", 3, responses)
    rows = drain(runtime, responses, 3)
    assert [r[1] for r in rows] == [0, 1, 2]
    assert all(r[4] for r in rows)
