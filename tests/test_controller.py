"""Guarded elastic fleet controller (ISSUE 20): the control loop's
guardrails -- hysteresis, cooldowns, bounded budget with loud refusal,
observe-mode dry run, fleet-epoch fencing -- plus the actuator seams
(stage/device inflight knobs, per-replica canary swap + rollback), the
FleetSupervisor respawn harness, and the pipeline integration (guarded
tick: controller death leaves the fleet serving).

The multi-process variant (real SIGKILL, real broker, a pilot whose
controller scales a real fleet) is the ``slow``-marked chaos driver
``--mode controller`` test at the bottom.
"""

import subprocess
import sys
import time

import pytest

from conftest import run_until

from aiko_services_tpu.orchestration.controller import (
    ACTION_KINDS, CONTROLLER_MODES, ControllerSpec, FleetController,
    FleetSupervisor, controller_spec_error, peer_definition)
from aiko_services_tpu.pipeline import DefinitionError, Pipeline
from aiko_services_tpu.pipeline.definition import \
    parse_pipeline_definition
from aiko_services_tpu.pipeline.stages import (REPLICA_DEAD,
                                               REPLICA_HALF_OPEN,
                                               REPLICA_LIVE,
                                               ReplicaGroup)

COMMON = "aiko_services_tpu.elements.common"


# -- fakes (the controller is duck-typed off the pipeline) ------------------

class Clock:
    def __init__(self, now=1000.0):
        self.now = now

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += seconds


class FakeQos:
    def __init__(self):
        self.max_inflight = 2
        self.overloaded_flag = False
        self.inflight = 0
        self.slo = None

    def overloaded(self):
        return self.overloaded_flag

    def stats(self):
        return {"inflight_total": self.inflight}


class FakeSlo:
    def __init__(self, burn=0.0):
        self.burn = burn

    def burn_rates(self):
        return {"default": {"standard": {"burn": self.burn}}}


class FakeScheduler:
    def __init__(self, depth=2):
        self.depth = depth
        self.stages = []
        self.groups = {}

    def waiting(self, stage):
        return 0


class FakeSupervisor:
    def __init__(self):
        self.spawned = []
        self.retired = []
        self._retiring = set()
        self.respawns = 0

    @property
    def size(self):
        return len(self.spawned) - len(self.retired)

    def names(self):
        return sorted(set(self.spawned) - set(self.retired))

    def spawn(self, name):
        self.spawned.append(name)

    def retire(self, name):
        self._retiring.add(name)
        self.retired.append(name)

    def destroy(self, name):
        if name not in self.retired:
            self.retire(name)

    @property
    def stats(self):
        return {"peers": self.names(), "respawns": self.respawns,
                "retired": len(self.retired), "retiring": []}


class FakePipeline:
    name = "fake"

    def __init__(self):
        self.share = {}
        self.qos = FakeQos()
        self.stage_scheduler = FakeScheduler()
        self.gateway = None
        self.telemetry = None
        self._draining = False
        self.bucket = "queue"
        self.frames = 50
        self.records = []
        self.blackboxes = []
        self.stage_inflight_calls = []
        self.device_inflight_calls = []
        self.parameters = {"device_inflight": 2}
        self.overrides = {}

    def explain(self):
        return {"bucket_share": {self.bucket: 0.8},
                "frames": self.frames}

    def _rec(self, etype, *arguments):
        self.records.append((etype, arguments))

    def _blackbox(self, reason, detail=""):
        self.blackboxes.append(reason)

    def _has_elastic_replicas(self):
        return False

    def set_stage_inflight(self, depth):
        self.stage_inflight_calls.append(depth)
        self.stage_scheduler.depth = depth
        return True

    def set_device_inflight(self, depth):
        self.device_inflight_calls.append(depth)
        self.parameters["device_inflight"] = depth
        return True

    def autoscale_replicas(self):
        return {}

    def get_pipeline_parameter(self, name, default=None):
        return self.parameters.get(name, default)

    def swap_replica_version(self, stage, index, name, value,
                             canary=True):
        key = (stage, index, name)
        old = self.overrides.get(key)
        if value is None:
            self.overrides.pop(key, None)
        else:
            self.overrides[key] = value
        group = self.stage_scheduler.groups.get(stage)
        if canary and group is not None:
            group.reopen(index)
        return old


def controller(pipeline, clock, **spec_overrides):
    spec_overrides.setdefault("mode", "act")
    spec_overrides.setdefault("hysteresis_ticks", 1)
    spec_overrides.setdefault("cooldown_ms", 0)
    spec_overrides.setdefault("fence_s", 5.0)
    spec = ControllerSpec(**spec_overrides)
    return FleetController(pipeline, spec, time_fn=clock)


def journaled(pipeline, etype):
    return [arguments for name, arguments in pipeline.records
            if name == etype]


# -- spec validation (create-time twin) -------------------------------------

def test_controller_spec_error_twin():
    assert controller_spec_error(None) is None
    assert controller_spec_error("observe") is None
    assert controller_spec_error("on") is None
    assert controller_spec_error(
        {"mode": "act", "fleet_max": 2, "interval_ms": 100}) is None

    problem = controller_spec_error({"bogus": 1})
    assert problem is not None and "bogus" in problem \
        and "known:" in problem
    problem = controller_spec_error({"hysteresis_ticks": 0})
    assert problem is not None and "hysteresis_ticks" in problem
    problem = controller_spec_error({"dominance": 1.5})
    assert problem is not None and "<= 1" in problem
    problem = controller_spec_error({"interval_ms": "soon"})
    assert problem is not None and "expected a number" in problem
    problem = controller_spec_error(
        {"fleet_min": 3, "fleet_max": 2})
    assert problem is not None and "fleet_max" in problem
    problem = controller_spec_error("sideways")
    assert problem is not None and "off|on|observe|act" in problem
    assert controller_spec_error(3.5) is not None
    assert controller_spec_error("{not json") is not None


def test_spec_parse_modes_and_flat_overlay():
    assert ControllerSpec.parse("on").mode == "act"
    assert ControllerSpec.parse("observe").mode == "observe"
    assert ControllerSpec.parse(None).mode == "off"
    spec = ControllerSpec.parse(
        {"mode": "on", "fleet_max": 2},
        {"controller_interval_ms": "100",
         "controller_hysteresis_ticks": "2", "fleet_max": "3"})
    assert spec.mode == "act"
    assert spec.interval_ms == 100.0
    assert spec.hysteresis_ticks == 2
    assert spec.fleet_max == 3            # flat spelling wins
    with pytest.raises(ValueError):
        ControllerSpec.parse({"mode": "act"},
                             {"controller_interval_ms": "soon"})
    with pytest.raises(ValueError):
        ControllerSpec.parse({"fleet_min": 2},
                             {"fleet_max": "1"})


# -- guardrails -------------------------------------------------------------

def test_observe_mode_journals_but_never_actuates():
    clock = Clock()
    pipeline = FakePipeline()
    loop = controller(pipeline, clock, mode="observe",
                      hysteresis_ticks=2)
    for _ in range(10):
        loop.tick()
        clock.advance(1.0)
    assert loop.actions_taken == 0
    assert not pipeline.stage_inflight_calls
    assert not pipeline.device_inflight_calls
    would = journaled(pipeline, "controller_would_act")
    assert would, "observe mode must journal the decisions it held"
    assert loop.status()["mode"] == "observe"


def test_hysteresis_damps_oscillating_diagnosis():
    clock = Clock()
    pipeline = FakePipeline()
    loop = controller(pipeline, clock, hysteresis_ticks=2)
    for index in range(20):
        # Square-wave attribution: the dominant bucket flips every
        # tick, so no diagnosis ever persists hysteresis_ticks.
        pipeline.bucket = ("queue", "pacing")[index % 2]
        loop.tick()
        clock.advance(0.5)
    assert loop.actions_taken == 0
    assert not pipeline.stage_inflight_calls


def test_steady_pressure_actuates_then_budget_refuses_loudly():
    clock = Clock()
    pipeline = FakePipeline()
    loop = controller(pipeline, clock, action_budget=2,
                      budget_window_s=300.0, knob_cap=8)
    for _ in range(10):
        loop.tick()
        clock.advance(1.0)
    assert loop.actions_taken == 2        # budget cap, not 10
    assert pipeline.stage_inflight_calls == [3, 4]
    assert loop.refusals > 0
    assert journaled(pipeline, "controller_refusal")
    assert "controller_refusal" in pipeline.blackboxes
    assert loop.status()["budget_left"] == 0


def test_cooldown_spaces_repeat_actions():
    clock = Clock()
    pipeline = FakePipeline()
    loop = controller(pipeline, clock, cooldown_ms=10000)
    loop.tick()
    assert loop.actions_taken == 1
    for _ in range(5):
        clock.advance(1.0)
        loop.tick()
    assert loop.actions_taken == 1        # cooling down: quiet skip
    clock.advance(10.0)
    loop.tick()
    assert loop.actions_taken == 2


def test_fence_on_fleet_epoch_change():
    clock = Clock()
    pipeline = FakePipeline()

    class Gateway:
        failovers = 0
    pipeline.gateway = Gateway()
    loop = controller(pipeline, clock, fence_s=5.0)
    loop.tick()
    assert loop.actions_taken == 1
    pipeline.gateway.failovers = 1        # failover mid-flight
    clock.advance(1.0)
    loop.tick()
    assert loop.actions_taken == 1
    assert loop.last.get("fenced")
    assert journaled(pipeline, "controller_fenced")
    # force_action respects the fence too
    problem = loop.force_action("stage_inflight")
    assert problem is not None and "fenced" in problem
    clock.advance(10.0)                   # fence expired
    loop.tick()
    assert loop.actions_taken == 2


def test_draining_pipeline_never_actuates():
    clock = Clock()
    pipeline = FakePipeline()
    loop = controller(pipeline, clock)
    pipeline._draining = True
    loop.tick()
    loop.tick()
    assert loop.actions_taken == 0
    assert loop.last.get("draining")


def test_pause_resume_and_force_action():
    clock = Clock()
    pipeline = FakePipeline()
    loop = controller(pipeline, clock, cooldown_ms=60000,
                      hysteresis_ticks=99)
    loop.pause()
    for _ in range(5):
        loop.tick()
        clock.advance(1.0)
    assert loop.actions_taken == 0
    loop.resume()
    # forced action bypasses hysteresis (99 ticks) and cooldown
    assert loop.force_action("stage_inflight", to=5) is None
    assert pipeline.stage_inflight_calls == [5]
    problem = loop.force_action("warp_drive")
    assert problem is not None and "unknown action" in problem
    assert set(ACTION_KINDS) >= {"spawn", "retire", "swap",
                                 "rollback"}
    assert CONTROLLER_MODES == ("off", "observe", "act")


# -- diagnosis tiers --------------------------------------------------------

def test_fetch_dominated_widens_device_inflight():
    clock = Clock()
    pipeline = FakePipeline()
    pipeline.bucket = "fetch"
    loop = controller(pipeline, clock)
    loop.tick()
    assert pipeline.device_inflight_calls == [3]
    # device_inflight 0 is an operator opt-out: never widened
    pipeline.parameters["device_inflight"] = 0
    clock.advance(1.0)
    loop.tick()
    assert pipeline.device_inflight_calls == [3]


def test_pacing_dominated_widens_qos_admission():
    clock = Clock()
    pipeline = FakePipeline()
    pipeline.bucket = "pacing"
    loop = controller(pipeline, clock, action_budget=100)
    loop.tick()
    assert pipeline.qos.max_inflight == 3
    # lazily capped at 4x the initial window: from 2, cap is 8
    for _ in range(20):
        clock.advance(1.0)
        loop.tick()
    assert pipeline.qos.max_inflight == 8


def test_spawn_tier_needs_overload_and_burn():
    clock = Clock()
    pipeline = FakePipeline()
    pipeline.qos.slo = FakeSlo(burn=5.0)
    supervisor = FakeSupervisor()
    spec = ControllerSpec(mode="act", hysteresis_ticks=1,
                          cooldown_ms=0, fleet_max=2)
    loop = FleetController(pipeline, spec, supervisor=supervisor,
                           time_fn=clock)
    loop.tick()                           # burning but NOT overloaded
    assert not supervisor.spawned
    pipeline.qos.overloaded_flag = True
    clock.advance(1.0)
    loop.tick()
    assert supervisor.spawned == ["fake-peer1"]
    assert loop.fleet_size() == 2
    clock.advance(1.0)
    loop.tick()                           # at fleet_max: no more
    assert supervisor.spawned == ["fake-peer1"]


def test_retire_tier_needs_full_idle():
    clock = Clock()
    pipeline = FakePipeline()
    pipeline.frames = 0                   # no dominant bucket signal
    pipeline.qos.slo = FakeSlo(burn=0.0)
    supervisor = FakeSupervisor()
    supervisor.spawn("fake-peer1")
    spec = ControllerSpec(mode="act", hysteresis_ticks=1,
                          cooldown_ms=0, fleet_max=2)
    loop = FleetController(pipeline, spec, supervisor=supervisor,
                           time_fn=clock)
    pipeline.qos.inflight = 1             # still busy: no retire
    loop.tick()
    assert not supervisor.retired
    pipeline.qos.inflight = 0
    clock.advance(1.0)
    loop.tick()
    assert supervisor.retired == ["fake-peer1"]


# -- canary-gated swap ------------------------------------------------------

def swap_fixture(watch_ticks=1):
    clock = Clock()
    pipeline = FakePipeline()
    pipeline.qos.slo = FakeSlo(burn=0.0)
    group = ReplicaGroup("work", 2, depth=2)
    pipeline.stage_scheduler.groups["work"] = group
    loop = controller(pipeline, clock,
                      canary_watch_ticks=watch_ticks,
                      canary_burn_ratio=1.5)
    return clock, pipeline, group, loop


def test_canary_swap_walks_every_replica():
    clock, pipeline, group, loop = swap_fixture()
    assert loop.begin_swap("work", "version", "v2") is None
    assert loop.begin_swap("work", "version", "v3") is not None
    # replica 0: swapped, demoted half-open awaiting its canary
    loop.tick()
    assert pipeline.overrides[("work", 0, "version")] == "v2"
    assert group.states[0] == REPLICA_HALF_OPEN
    group.states[0] = REPLICA_LIVE        # canary delivered OK
    clock.advance(1.0)
    loop.tick()                           # watch tick passes
    clock.advance(1.0)
    loop.tick()                           # replica 1 swapped
    assert pipeline.overrides[("work", 1, "version")] == "v2"
    group.states[1] = REPLICA_LIVE
    clock.advance(1.0)
    loop.tick()
    clock.advance(1.0)
    loop.tick()
    assert loop.swap is None              # swap complete
    assert journaled(pipeline, "controller_swap_done")
    assert loop.rollbacks == 0


def test_canary_death_rolls_back_every_swapped_replica():
    clock, pipeline, group, loop = swap_fixture()
    pipeline.overrides[("work", 0, "version")] = "v1"
    pipeline.overrides[("work", 1, "version")] = "v1"
    assert loop.begin_swap("work", "version", "v2") is None
    loop.tick()                           # replica 0 swapped
    group.states[0] = REPLICA_LIVE
    clock.advance(1.0)
    loop.tick()
    clock.advance(1.0)
    loop.tick()                           # replica 1 swapped
    assert pipeline.overrides[("work", 1, "version")] == "v2"
    group.states[1] = REPLICA_DEAD        # its canary failed
    clock.advance(1.0)
    loop.tick()
    assert loop.swap is None
    assert loop.rollbacks == 1
    # BOTH replicas restored to the pre-swap value
    assert pipeline.overrides[("work", 0, "version")] == "v1"
    assert pipeline.overrides[("work", 1, "version")] == "v1"
    assert "canary_rollback" in pipeline.blackboxes
    assert journaled(pipeline, "controller_rollback")


def test_burn_above_baseline_ratio_rolls_back():
    clock, pipeline, group, loop = swap_fixture(watch_ticks=3)
    assert loop.begin_swap("work", "version", "v2") is None
    loop.tick()
    group.states[0] = REPLICA_LIVE
    clock.advance(1.0)
    loop.tick()                           # watch 1: burn fine
    pipeline.qos.slo.burn = 4.0           # canary burning the budget
    clock.advance(1.0)
    loop.tick()
    assert loop.swap is None
    assert loop.rollbacks == 1
    assert ("work", 0, "version") not in pipeline.overrides


def test_swap_refusals():
    clock, pipeline, group, loop = swap_fixture()
    assert "not replicated" in loop.begin_swap("decode", "v", 1)
    group.states[:] = [REPLICA_DEAD, REPLICA_DEAD]
    assert "no live replicas" in loop.begin_swap("work", "v", 1)
    loop.spec.mode = "observe"
    group.states[:] = [REPLICA_LIVE, REPLICA_LIVE]
    assert "refusing" in loop.begin_swap("work", "v", 1)


# -- FleetSupervisor (respawn-on-death harness) -----------------------------

def sleeper_spawner(log):
    def spawn(name):
        process = subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(60)"])
        log.append((name, process.pid))
        return process
    return spawn


def wait_until(predicate, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return predicate()


def test_supervisor_respawns_after_sigkill():
    log = []
    supervisor = FleetSupervisor(sleeper_spawner(log), engine=None,
                                 backoff_s=0.05)
    try:
        process = supervisor.spawn("peer1")
        assert supervisor.size == 1
        process.kill()
        assert wait_until(lambda: supervisor.respawns >= 1
                          and supervisor.manager.get("peer1")
                          is not None)
        assert [name for name, _ in log] == ["peer1", "peer1"]
        assert supervisor.stats["respawns"] >= 1
    finally:
        supervisor.stop_all(5.0)
    assert wait_until(
        lambda: all(subprocess.Popen.poll(
            supervisor.manager.get("peer1") or process) is not None
            for _ in (0,)), timeout=10.0)


def test_supervisor_retire_suppresses_respawn():
    log = []
    supervisor = FleetSupervisor(sleeper_spawner(log), engine=None,
                                 backoff_s=0.05)
    try:
        process = supervisor.spawn("peer1")
        supervisor.retire("peer1")
        process.kill()
        assert wait_until(lambda: supervisor.retired >= 1)
        time.sleep(0.3)                   # a respawn would land here
        assert supervisor.respawns == 0
        assert len(log) == 1
    finally:
        supervisor.stop_all(5.0)


def test_supervisor_backoff_doubles_then_caps():
    clock = Clock()
    supervisor = FleetSupervisor(lambda name: None, engine=None,
                                 backoff_s=0.5, backoff_max_s=4.0,
                                 stable_s=30.0, time_fn=clock)
    supervisor._started["x"] = clock()
    # Three quick deaths: the recorded next-delay doubles, capped.
    supervisor._backoff.pop("x", None)
    for expected in (1.0, 2.0, 4.0, 4.0):
        # simulate the bookkeeping _on_exit does, without processes
        delay = supervisor._backoff.get("x", supervisor.backoff_s)
        supervisor._backoff["x"] = min(supervisor.backoff_max_s,
                                       delay * 2.0)
        assert supervisor._backoff["x"] == expected


# -- peer_definition --------------------------------------------------------

def test_peer_definition_strips_singleton_planes():
    definition = parse_pipeline_definition({
        "version": 0, "name": "pilot", "runtime": "jax",
        "graph": ["(work)"],
        "parameters": {"journal": "on", "journal_dir": "/tmp/j",
                       "gateway": "on", "metrics_port": 0,
                       "controller": {"mode": "act", "fleet_max": 3},
                       "controller_interval_ms": 100,
                       "stage_inflight": 4},
        "elements": [{"name": "work", "input": [{"name": "x"}],
                      "output": [{"name": "x"}],
                      "parameters": {"busy_ms": 1.0},
                      "placement": {"devices": 2},
                      "deploy": {"local": {"module": COMMON,
                                           "class_name":
                                               "StageWork"}}}]})
    peer = peer_definition(definition, "pilot-peer1",
                           journal_dir="/tmp/j")
    assert peer["name"] == "pilot-peer1"
    assert peer["parameters"]["controller"] == "off"
    assert peer["parameters"]["gateway"] == "off"
    assert "controller_interval_ms" not in peer["parameters"]
    assert "metrics_port" not in peer["parameters"]
    assert peer["parameters"]["journal_dir"] == "/tmp/j"
    assert peer["parameters"]["stage_inflight"] == 4
    # round-trips through the parser (a spawned peer can load it)
    reparsed = parse_pipeline_definition(peer)
    assert reparsed.element("work").deploy_local["class_name"] \
        == "StageWork"


# -- pipeline integration ---------------------------------------------------

def stage(name, busy_ms=1.0, factor=2.0):
    return {"name": name, "input": [{"name": "x"}],
            "output": [{"name": "x"}],
            "parameters": {"busy_ms": busy_ms, "factor": factor},
            "placement": {"devices": 2},
            "deploy": {"local": {"module": COMMON,
                                 "class_name": "StageWork"}}}


def serving(runtime, name, extra=None):
    parameters = {"controller": "observe",
                  "controller_interval_ms": 50}
    parameters.update(extra or {})
    return Pipeline({"version": 0, "name": name, "runtime": "jax",
                     "graph": ["(work finish)"],
                     "parameters": parameters,
                     "elements": [stage("work"),
                                  stage("finish", factor=3.0)]},
                    runtime=runtime)


def stream_through(runtime, pipeline, count=3):
    import queue

    import numpy as np
    responses = queue.Queue()
    pipeline.create_stream_local("s1", queue_response=responses)
    for index in range(count):
        pipeline.process_frame_local(
            {"x": np.asarray([float(index + 1)], np.float32)},
            stream_id="s1")
    run_until(runtime, lambda: responses.qsize() >= count,
              timeout=30.0)
    return [responses.get() for _ in range(count)]


def test_bad_controller_block_is_definition_error(runtime, tmp_path):
    with pytest.raises(DefinitionError, match="bogus"):
        serving(runtime, "bad",
                extra={"controller": {"bogus": 1},
                       "preflight": "off"})
    with pytest.raises(DefinitionError, match="fleet_max"):
        serving(runtime, "bad2",
                extra={"controller": {"mode": "act", "fleet_min": 3,
                                      "fleet_max": 2},
                       "preflight": "off"})


def test_controller_death_leaves_pipeline_serving(runtime):
    pipeline = serving(runtime, "guarded")
    try:
        assert pipeline.controller is not None
        assert pipeline.controller.spec.mode == "observe"

        def explode():
            raise RuntimeError("controller bug")
        pipeline.controller.tick = explode
        pipeline._controller_tick()       # the guarded timer body
        assert pipeline.controller.paused is True
        # the fleet keeps serving exactly as tuned
        done = stream_through(runtime, pipeline)
        assert len(done) == 3
    finally:
        pipeline.stop()


def test_controller_ticks_on_live_pipeline(runtime):
    pipeline = serving(runtime, "ticking")
    try:
        run_until(runtime,
                  lambda: pipeline.controller.ticks >= 2,
                  timeout=10.0)
        assert pipeline.controller.ticks >= 2
        assert pipeline.share["fleet_size"] == 1
        status = pipeline.controller.status()
        assert status["mode"] == "observe"
        assert status["actions"] == 0
    finally:
        pipeline.stop()


def test_stage_and_device_inflight_knobs(runtime):
    pipeline = serving(runtime, "knobs",
                       extra={"stage_inflight": 2})
    try:
        scheduler = pipeline.stage_scheduler
        assert scheduler is not None and scheduler.depth == 2
        assert pipeline.set_stage_inflight(4) is True
        assert scheduler.depth == 4
        assert pipeline.get_pipeline_parameter("stage_inflight") == 4
        assert pipeline.set_stage_inflight(4) is False  # no-op
        assert pipeline.set_device_inflight(4) is True
        assert pipeline.get_pipeline_parameter("device_inflight") == 4
        done = stream_through(runtime, pipeline)
        assert len(done) == 3
    finally:
        pipeline.stop()


def test_replica_override_resolves_per_replica(runtime):
    pipeline = serving(runtime, "overrides")
    try:
        old = pipeline.swap_replica_version("work", 0, "factor", 5.0,
                                            canary=False)
        assert old is None
        value, found = pipeline.replica_override("work", 0, "factor")
        assert found and value == 5.0
        # the other replica index is untouched
        _, found = pipeline.replica_override("work", 1, "factor")
        assert not found
        # rollback round-trips through the returned previous value
        previous = pipeline.swap_replica_version(
            "work", 0, "factor", old, canary=False)
        assert previous == 5.0
        _, found = pipeline.replica_override("work", 0, "factor")
        assert not found
    finally:
        pipeline.stop()


def test_fleetctl_wire_surface(runtime):
    pipeline = serving(runtime, "wired")
    replies = []
    topic = "test/fleetctl/reply"

    def on_reply(topic_in, payload):
        replies.append(payload)

    runtime.add_message_handler(on_reply, topic)
    try:
        pipeline.fleetctl(topic, "status")
        run_until(runtime, lambda: len(replies) >= 2, timeout=5.0)
        assert any("fleetctl" in reply for reply in replies)
        import json as json_module

        from aiko_services_tpu.utils import parse
        payload = next(reply for reply in replies
                       if "fleetctl" in reply)
        command, parameters = parse(payload)
        report = json_module.loads(str(parameters[0]))
        assert report["mode"] == "observe"
        replies.clear()
        pipeline.fleetctl(topic, "pause")
        assert pipeline.controller.paused is True
        pipeline.fleetctl(topic, "resume")
        assert pipeline.controller.paused is False
        pipeline.fleetctl(topic, "bogus")
        run_until(runtime, lambda: len(replies) >= 6, timeout=5.0)
        last = json_module.loads(
            str(parse(replies[-1])[1][0]))
        assert "unknown fleetctl command" in last["error"]
    finally:
        runtime.remove_message_handler(on_reply, topic)
        pipeline.stop()


# -- multi-process walk (slow) ----------------------------------------------

@pytest.mark.slow
def test_chaos_controller_mode_converges():
    from aiko_services_tpu.faults.chaos import run_chaos

    result = run_chaos(frames=8, mode="controller", busy_ms=50.0,
                       timeout=240.0, echo=lambda *_: None)
    assert result["ok"], result
    assert result["fleet_grew"] and result["respawned"]
    assert result["dropped"] == 0
