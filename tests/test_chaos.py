"""Fault-injection harness + end-to-end failure recovery (ISSUE 5).

The acceptance contract: with the harness injecting (a) chip death
mid-flight, (b) remote-stage death mid-park, (c) overload on a live
stream, every stream either completes or errors within its deadline --
zero hung streams -- with ``frames_replayed``/``frames_shed``/breaker
transitions proving WHICH recovery path ran, and all injection points
proven no-ops (probe counter unchanged) when no FaultPlan is armed.

Plans are deterministic: rules fire by exact after/count bookkeeping
(prob-rules seeded), so every assertion is on an exact blast radius.
"""

import queue
import time

import jax
import numpy as np
import pytest

from conftest import run_until

from aiko_services_tpu import faults as faults_module
from aiko_services_tpu.faults import (BREAKER_CLOSED, BREAKER_OPEN,
                                      CircuitBreaker, FaultPlan,
                                      probe_count)
from aiko_services_tpu.pipeline import Pipeline, PipelineElement, \
    StreamEvent
from aiko_services_tpu.pipeline.tensor import TPUElement
from aiko_services_tpu.services import Registrar

pytestmark = pytest.mark.chaos


# -- elements loaded by module path ------------------------------------------


class BusyStage(TPUElement):
    """Placed synchronous stage: jitted multiply + host wait, the shape
    that parks frames on stage workers."""

    def process_frame(self, stream, x):
        busy_ms, _ = self.get_parameter("busy_ms", 20.0)
        compute = self.jit(lambda a: a * 2.0)
        y = compute(x)
        time.sleep(float(busy_ms) / 1000.0)
        return StreamEvent.OKAY, {"x": y}


class SlowAsyncEcho(PipelineElement):
    """Async element completing from a worker thread after a delay --
    the parked-async shape for mid-park replacement."""

    is_async = True

    def process_frame_start(self, stream, complete, **inputs):
        import threading

        delay_ms, _ = self.get_parameter("delay_ms", 50.0)

        def finish():
            time.sleep(float(delay_ms) / 1000.0)
            complete(StreamEvent.OKAY, dict(inputs))

        threading.Thread(target=finish, daemon=True).start()


class SlowAsyncAdd(PipelineElement):
    """Async +1000 after a delay: its contribution is value-visible, so
    a duplicate remote response overwriting its park shows up as a
    wrong number, not just a timing blip."""

    is_async = True

    def process_frame_start(self, stream, complete, x=None, **inputs):
        import threading

        delay_ms, _ = self.get_parameter("delay_ms", 50.0)

        def finish():
            time.sleep(float(delay_ms) / 1000.0)
            complete(StreamEvent.OKAY, {"x": int(x) + 1000})

        threading.Thread(target=finish, daemon=True).start()


class CheapLocal(PipelineElement):
    """Degraded-mode fallback: tags its output so tests can tell the
    fallback ran instead of the remote."""

    def process_frame(self, stream, x=None, **inputs):
        return StreamEvent.OKAY, {"x": int(x) + 100}


def element(name, cls, inputs=("x",), outputs=("x",), parameters=None,
            placement=None, module="tests/test_chaos.py"):
    entry = {"name": name,
             "input": [{"name": n} for n in inputs],
             "output": [{"name": n} for n in outputs],
             "parameters": parameters or {},
             "deploy": {"local": {"module": module, "class_name": cls}}}
    if placement:
        entry["placement"] = placement
    return entry


def ingest(pipeline, responses, count, stream_id="0", value=None):
    for i in range(count):
        data = {"x": np.float32(i + 1) if value is None else value}
        pipeline.process_frame_local(data, stream_id=stream_id,
                                     queue_response=responses)


def collect(runtime, responses, count, timeout=60.0):
    rows = []

    def drained():
        while not responses.empty():
            rows.append(responses.get())
        return len(rows) >= count

    run_until(runtime, drained, timeout=timeout)
    return rows


# -- FaultPlan / breaker units -----------------------------------------------


def test_fault_plan_parse_and_counting():
    plan = FaultPlan.parse({"seed": 7, "rules": [
        {"point": "element_raise", "target": "det", "after": 1,
         "count": 2},
        {"point": "wire_drop", "target": "process_frame",
         "count": None}]})
    assert plan.should("element_raise", target="llm") is None
    assert plan.should("element_raise", target="det") is None  # after=1
    assert plan.should("element_raise", target="det") is not None
    assert plan.should("element_raise", target="det") is not None
    assert plan.should("element_raise", target="det") is None  # count=2
    # unbounded rule + topic substring matching
    for _ in range(3):
        assert plan.should("wire_drop", target="process_frame") \
            is not None
    assert plan.fired("element_raise") == 2
    assert plan.fired("wire_drop") == 3
    assert len(plan.trace) == 5
    assert plan.probes == 8


def test_fault_plan_rejects_unknown_point_and_fields():
    with pytest.raises(ValueError, match="not one of"):
        FaultPlan.parse([{"point": "nope"}])
    with pytest.raises(ValueError, match="unknown fields"):
        FaultPlan.parse([{"point": "wire_drop", "bogus": 1}])


def test_fault_plan_seeded_prob_is_deterministic():
    def fires(seed):
        plan = FaultPlan.parse({"seed": seed, "rules": [
            {"point": "element_raise", "count": None, "prob": 0.5}]})
        return [plan.should("element_raise") is not None
                for _ in range(32)]

    assert fires(3) == fires(3)
    assert fires(3) != fires(4)


def test_circuit_breaker_state_walk():
    now = [0.0]
    breaker = CircuitBreaker(threshold=2, cooldown_s=1.0,
                             clock=lambda: now[0])
    assert breaker.allow() and breaker.state == BREAKER_CLOSED
    breaker.record_failure()
    assert breaker.state == BREAKER_CLOSED          # 1 < threshold
    breaker.record_failure()
    assert breaker.state == BREAKER_OPEN
    assert not breaker.allow()                      # cooling down
    now[0] = 1.5
    assert breaker.allow()                          # half-open probe
    assert breaker.state == "half_open"
    assert not breaker.allow()                      # one probe at a time
    breaker.record_failure()                        # probe failed
    assert breaker.state == BREAKER_OPEN
    now[0] = 3.0
    assert breaker.allow()
    breaker.record_success()                        # probe succeeded
    assert breaker.state == BREAKER_CLOSED
    assert [s for s, _ in breaker.transitions] == \
        ["open", "half_open", "open", "half_open", "closed"]


def test_circuit_breaker_halfopen_probe_timeout_allows_reprobe():
    now = [0.0]
    breaker = CircuitBreaker(threshold=1, cooldown_s=1.0,
                             clock=lambda: now[0])
    breaker.record_failure()
    now[0] = 1.1
    assert breaker.allow()          # probe 1 -- then it goes silent
    now[0] = 2.3
    assert breaker.allow()          # probe window expired: probe 2


# -- no-op when unarmed ------------------------------------------------------


def test_unarmed_pipeline_never_enters_the_harness(runtime):
    """Acceptance: with no FaultPlan armed, zero injection-point
    branches are taken (module probe counter unchanged) across a full
    placed stage-parallel run."""
    pipeline = Pipeline(
        {"version": 0, "name": "p_noop", "runtime": "jax",
         "graph": ["(det llm)"],
         "parameters": {},
         "elements": [
             element("det", "BusyStage", parameters={"busy_ms": 1.0},
                     placement={"devices": 4}),
             element("llm", "BusyStage", parameters={"busy_ms": 1.0},
                     placement={"devices": 4})]},
        runtime=runtime)
    before = probe_count()
    responses = queue.Queue()
    ingest(pipeline, responses, 4)
    rows = collect(runtime, responses, 4)
    assert len(rows) == 4 and all(row[4] for row in rows)
    assert probe_count() == before
    assert pipeline.fault_stats()["armed"] is False
    pipeline.stop()


# -- (a) chip death mid-flight -----------------------------------------------


def test_chip_death_midflight_replays_parked_stage_frames(runtime):
    """Frames parked on a placed stage worker when replace() fires are
    replayed onto the replacement submeshes and complete -- no hung
    stream, no errored stream, frames_replayed > 0."""
    pipeline = Pipeline(
        {"version": 0, "name": "p_replay", "runtime": "jax",
         "graph": ["(det llm)"],
         "parameters": {"replay_limit": 3},
         "elements": [
             element("det", "BusyStage", parameters={"busy_ms": 30.0},
                     placement={"devices": 4}),
             element("llm", "BusyStage", parameters={"busy_ms": 30.0},
                     placement={"devices": 4})]},
        runtime=runtime)
    responses = queue.Queue()
    ingest(pipeline, responses, 6)
    # Kill two of det's chips while frames are mid-stage: the posts
    # interleave with the frames' stage-worker parks.
    dead = list(pipeline.stage_placement.plans["det"]
                .mesh.devices.flat)[:2]
    # Small delay so the kill lands while frames occupy stage credits
    # and worker threads, not just the admission queue.
    pipeline.post_self("replace_failed_devices", [dead], delay=0.05)
    rows = collect(runtime, responses, 6)
    assert len(rows) == 6, "stream hung after mid-flight replacement"
    assert all(row[4] for row in rows), \
        [row[5] for row in rows if not row[4]]
    assert pipeline.share["frames_replayed"] > 0
    assert pipeline.stage_placement.generation == 1
    assert not (set(pipeline.stage_placement.devices) & set(dead))
    # In-order delivery survived the replay.
    order = [row[1] for row in rows]
    assert order == sorted(order)
    pipeline.stop()


def test_dispatch_raise_probe_replace_recovers_sync_element(runtime):
    """The dispatch-time story: an element raises (injected XLA 'chip
    died' error), the engine probes, the armed device_kill rule marks
    the stage's chips dead, replace() fires and the frame replays to
    completion -- one frame, one replay, zero stream errors."""
    pipeline = Pipeline(
        {"version": 0, "name": "p_dispatch", "runtime": "jax",
         "graph": ["(sq)"],
         "parameters": {
             "health_probe_timeout": 2.0,
             "fault_plan": {"rules": [
                 {"point": "element_raise", "target": "sq", "count": 1},
                 {"point": "device_kill", "target": "sq", "count": 1},
             ]}},
         "elements": [element("sq", "BusyStage",
                              parameters={"busy_ms": 0.0},
                              placement={"mesh": {"dp": 4}})]},
        runtime=runtime)
    responses = queue.Queue()
    ingest(pipeline, responses, 1)
    rows = collect(runtime, responses, 1)
    assert rows and rows[0][4], rows[0][5]
    assert pipeline.share["frames_replayed"] == 1
    assert pipeline.stage_placement.generation == 1
    plan_stats = pipeline.fault_stats()["plan"]
    assert plan_stats["fired"] == {"element_raise": 1, "device_kill": 1}
    pipeline.stop()


def test_chip_death_midpark_async_replays_and_discards_stale(runtime):
    """A frame parked at an async element when chips die replays from
    the async stage; the pre-replay completion post is discarded by the
    replay-epoch guard (it must not double-run the suffix)."""
    pipeline = Pipeline(
        {"version": 0, "name": "p_async", "runtime": "jax",
         "graph": ["(up echo)"],
         "parameters": {},
         "elements": [
             element("up", "BusyStage", parameters={"busy_ms": 0.0},
                     placement={"mesh": {"dp": 4}}),
             element("echo", "SlowAsyncEcho",
                     parameters={"delay_ms": 150.0})]},
        runtime=runtime)
    responses = queue.Queue()
    ingest(pipeline, responses, 1)
    # Let the frame reach the async park, then kill half the chips.
    stream_holder = {}

    def parked():
        stream = pipeline.streams.get("0")
        if stream is None:
            return False
        stream_holder["stream"] = stream
        frame = stream.frames.get(0)
        return frame is not None and frame.paused_pe_name == "echo"

    assert run_until(runtime, parked, timeout=10.0)
    dead = pipeline.stage_placement.devices[:2]
    pipeline.post_self("replace_failed_devices", [dead])
    rows = collect(runtime, responses, 1)
    assert rows and rows[0][4], rows[0][5]
    assert len(rows) == 1                   # stale completion discarded
    assert pipeline.share["frames_replayed"] == 1
    assert rows[0][3].get("replays") == 1
    pipeline.stop()


def test_replay_limit_bounds_repeated_replacement(runtime):
    """A frame caught by replace() more times than replay_limit errors
    with a clear diagnostic instead of replaying forever."""
    pipeline = Pipeline(
        {"version": 0, "name": "p_limit", "runtime": "jax",
         "graph": ["(up echo)"],
         "parameters": {"replay_limit": 1},
         "elements": [
             element("up", "BusyStage", parameters={"busy_ms": 0.0},
                     placement={"mesh": {"dp": 8}}),
             element("echo", "SlowAsyncEcho",
                     parameters={"delay_ms": 200.0})]},
        runtime=runtime)
    responses = queue.Queue()
    ingest(pipeline, responses, 1)

    def parked():
        stream = pipeline.streams.get("0")
        frame = stream.frames.get(0) if stream else None
        return frame is not None and frame.paused_pe_name == "echo"

    assert run_until(runtime, parked, timeout=10.0)
    devices = list(pipeline.stage_placement.devices)
    pipeline.post_self("replace_failed_devices", [devices[:2]])
    assert run_until(runtime, parked, timeout=10.0)  # replay re-parked
    pipeline.post_self("replace_failed_devices", [devices[2:4]])
    rows = collect(runtime, responses, 1)
    assert rows and not rows[0][4]
    assert "replay limit" in rows[0][5]
    pipeline.stop()


def test_segment_fail_midflight_recovers_fused_chain(runtime):
    """Chip death presenting inside a FUSED dispatch (non-compiling
    call raises): the probe finds the dead chips, segments rebuild for
    the new generation, and the frame replays per-element to the same
    answer."""
    pipeline = Pipeline(
        {"version": 0, "name": "p_seg", "runtime": "jax",
         "graph": ["(d1 d2)"],
         "parameters": {
             "health_probe_timeout": 2.0,
             "fault_plan": {"rules": [
                 # after=1: the first (compiling) dispatch succeeds so
                 # the segment is established; the second frame's
                 # warm-cache dispatch takes the injected failure.
                 {"point": "segment_fail", "target": "d1+d2",
                  "after": 1, "count": 1},
                 {"point": "device_kill", "target": "device:0",
                  "count": 1},
             ]}},
         "elements": [
             element("d1", "DeviceDouble",
                     module="tests/test_fusion.py"),
             element("d2", "DeviceAddOne",
                     module="tests/test_fusion.py"),
             # Off-graph placement block so a StagePlacement exists for
             # the probe to replace (the fused chain itself is
             # unplaced; stage plans come from element definitions).
             element("sink", "BusyStage",
                     parameters={"busy_ms": 0.0},
                     placement={"mesh": {"dp": 4}})]},
        runtime=runtime)
    responses = queue.Queue()
    ingest(pipeline, responses, 2, value=np.float32(3.0))
    rows = collect(runtime, responses, 2)
    assert len(rows) == 2
    assert all(row[4] for row in rows), \
        [row[5] for row in rows if not row[4]]
    for row in rows:
        assert float(np.asarray(row[2]["x"])) == 7.0     # 3*2+1
    assert pipeline.share["frames_replayed"] == 1
    assert pipeline.fault_stats()["plan"]["fired"]["segment_fail"] == 1
    pipeline.stop()


# -- (b) remote-stage death mid-park: breaker + deadlines --------------------


def _remote_pair_defs(fallback=False):
    front_elements = [
        {"name": "inc", "input": [{"name": "x"}],
         "output": [{"name": "x"}],
         "deploy": {"local": {
             "module": "aiko_services_tpu.elements.common",
             "class_name": "Increment"}}},
        {"name": "fwd", "input": [{"name": "x"}],
         "output": [{"name": "x"}],
         "deploy": {"remote": {"name": "back"}}}]
    if fallback:
        front_elements[1]["fallback"] = "cheap"
        front_elements.append(element("cheap", "CheapLocal"))
    front = {"version": 0, "name": "front", "runtime": "jax",
             "graph": ["(inc fwd)"],
             "parameters": {"frame_deadline_ms": 400,
                            "breaker_threshold": 2,
                            "breaker_cooldown_ms": 250},
             "elements": front_elements}
    back = {"version": 0, "name": "back", "runtime": "jax",
            "graph": ["(inc)"],
            "elements": [front_elements[0]]}
    return front, back


def test_remote_death_midpark_breaker_opens_and_recloses(runtime):
    """Responses dropped on the wire -> parked frames deadline-error ->
    breaker opens (frames fail fast, stream stays alive) -> half-open
    probe succeeds once the wire heals -> breaker recloses and frames
    flow.  Zero hung streams; every frame completed or errored within
    its deadline."""
    Registrar(runtime=runtime, primary_search_timeout=0.05)
    front_def, back_def = _remote_pair_defs()
    front = Pipeline(front_def, runtime=runtime)
    back = Pipeline(back_def, runtime=runtime)
    responses = queue.Queue()
    # Warm the remote path (discovery + first round trip) on a
    # deadline-free stream so discovery latency can't flake the warmup.
    front.create_stream_local("w", {"frame_deadline_ms": 0},
                              queue_response=responses)
    front.ingest_local("w", {"x": 0}, queue_response=responses)
    warm = collect(runtime, responses, 1)
    assert warm and warm[0][4], warm[0]
    front.create_stream_local("1", queue_response=responses)

    # Drop the next TWO responses: two deadline misses open the breaker.
    front.arm_faults({"rules": [
        {"point": "wire_drop", "target": "process_frame_response",
         "count": 2}]})
    for _ in range(2):
        front.ingest_local("1", {"x": 0}, queue_response=responses)
        rows = collect(runtime, responses, 1, timeout=10.0)
        assert rows and not rows[0][4]
        assert "deadline" in rows[0][5]
    breaker = front.breakers["fwd"]
    assert breaker.state == BREAKER_OPEN
    assert front.share["deadline_misses"] == 2

    # Breaker open: the next frame fails FAST (no deadline wait, no
    # wire traffic) and the stream survives.
    start = time.monotonic()
    front.ingest_local("1", {"x": 0}, queue_response=responses)
    rows = collect(runtime, responses, 1, timeout=10.0)
    assert rows and not rows[0][4]
    assert "circuit breaker open" in rows[0][5]
    assert time.monotonic() - start < 0.35      # < deadline: fail-fast
    assert "1" in front.streams                  # stream alive

    # Cooldown elapses; the wire is healthy again (count=2 exhausted):
    # the half-open probe round-trips and recloses the breaker.
    time.sleep(0.3)
    front.ingest_local("1", {"x": 10}, queue_response=responses)
    rows = collect(runtime, responses, 1, timeout=10.0)
    assert rows and rows[0][4], rows[0][5]
    assert int(rows[0][2]["x"]) == 12            # inc + remote inc
    assert breaker.state == BREAKER_CLOSED
    walk = [s for s, _ in breaker.transitions]
    assert walk == ["open", "half_open", "closed"]
    assert front.fault_stats()["plan"]["fired"]["wire_drop"] == 2
    front.stop()
    back.stop()


def test_breaker_open_runs_declared_fallback(runtime):
    """With a ``fallback:`` declared, an open breaker degrades to the
    local element instead of failing the frame."""
    Registrar(runtime=runtime, primary_search_timeout=0.05)
    front_def, back_def = _remote_pair_defs(fallback=True)
    front = Pipeline(front_def, runtime=runtime)
    back = Pipeline(back_def, runtime=runtime)
    responses = queue.Queue()
    front.create_stream_local("w", {"frame_deadline_ms": 0},
                              queue_response=responses)
    front.ingest_local("w", {"x": 0}, queue_response=responses)
    warm = collect(runtime, responses, 1)
    assert warm and warm[0][4]
    front.create_stream_local("1", queue_response=responses)

    front.arm_faults({"rules": [
        {"point": "wire_drop", "target": "process_frame_response",
         "count": 2}]})
    for _ in range(2):
        front.ingest_local("1", {"x": 0}, queue_response=responses)
        rows = collect(runtime, responses, 1, timeout=10.0)
        assert rows and not rows[0][4]
    assert front.breakers["fwd"].state == BREAKER_OPEN

    front.ingest_local("1", {"x": 5}, queue_response=responses)
    rows = collect(runtime, responses, 1, timeout=10.0)
    assert rows and rows[0][4], rows[0][5]
    # inc (5->6) then CheapLocal fallback (+100), not the remote inc.
    assert int(rows[0][2]["x"]) == 106
    assert rows[0][3].get("breaker_fallbacks") == 1
    front.stop()
    back.stop()


def test_wire_dup_response_never_resumes_a_local_park(runtime):
    """A duplicated remote response (wire_dup fault, MQTT QoS1
    redelivery) must be discarded once the frame has moved past the
    remote stage -- mapping remote outputs under a LOCAL element would
    silently replace its real result."""
    Registrar(runtime=runtime, primary_search_timeout=0.05)
    back = Pipeline(
        {"version": 0, "name": "back", "runtime": "jax",
         "graph": ["(inc)"],
         "elements": [{"name": "inc", "input": [{"name": "x"}],
                       "output": [{"name": "x"}],
                       "deploy": {"local": {
                           "module": "aiko_services_tpu.elements.common",
                           "class_name": "Increment"}}}]},
        runtime=runtime)
    front = Pipeline(
        {"version": 0, "name": "front", "runtime": "jax",
         "graph": ["(fwd post)"],
         "elements": [
             {"name": "fwd", "input": [{"name": "x"}],
              "output": [{"name": "x"}],
              "deploy": {"remote": {"name": "back"}}},
             {"name": "post", "input": [{"name": "x"}],
              "output": [{"name": "x"}],
              "parameters": {"delay_ms": 60.0},
              "deploy": {"local": {"module": "tests/test_chaos.py",
                                   "class_name": "SlowAsyncAdd"}}}]},
        runtime=runtime)
    responses = queue.Queue()
    front.create_stream_local("1", queue_response=responses)
    front.ingest_local("1", {"x": 0}, queue_response=responses)
    rows = collect(runtime, responses, 1)
    assert rows and rows[0][4], rows[0]

    front.arm_faults({"rules": [
        {"point": "wire_dup", "target": "process_frame_response",
         "count": 1}]})
    front.ingest_local("1", {"x": 10}, queue_response=responses)
    rows = collect(runtime, responses, 1, timeout=15.0)
    assert len(rows) == 1                   # duplicate never delivered
    assert rows[0][4], rows[0][5]
    # remote inc once (10 -> 11) THEN the async +1000: a duplicate
    # response short-circuiting post's park would deliver 11.
    assert int(rows[0][2]["x"]) == 1011
    assert front.fault_stats()["plan"]["fired"]["wire_dup"] == 1
    front.stop()
    back.stop()


def test_remote_retry_limit_errors_with_clear_message(runtime):
    """An undiscovered remote bounded by remote_retry_limit errors the
    frame with an actionable diagnostic; limit 0 keeps the unbounded
    pre-existing behavior."""
    Registrar(runtime=runtime, primary_search_timeout=0.05)
    front = Pipeline(
        {"version": 0, "name": "front", "runtime": "jax",
         "graph": ["(fwd)"],
         "parameters": {"remote_retry_limit": 2},
         "elements": [
             {"name": "fwd", "input": [{"name": "x"}],
              "output": [{"name": "x"}],
              "deploy": {"remote": {"name": "nowhere"}}}]},
        runtime=runtime)
    responses = queue.Queue()
    front.create_stream_local("1", queue_response=responses)
    front.ingest_local("1", {"x": 0}, queue_response=responses)
    rows = collect(runtime, responses, 1, timeout=30.0)
    assert rows and not rows[0][4]
    assert "remote_retry_limit=2" in rows[0][5]
    assert "is the remote pipeline running?" in rows[0][5]
    front.stop()

    # limit 0: unbounded -- the frame stays parked, stream alive.
    unbounded = Pipeline(
        {"version": 0, "name": "front0", "runtime": "jax",
         "graph": ["(fwd)"],
         "parameters": {"remote_retry_limit": 0},
         "elements": [
             {"name": "fwd", "input": [{"name": "x"}],
              "output": [{"name": "x"}],
              "deploy": {"remote": {"name": "nowhere"}}}]},
        runtime=runtime)
    responses = queue.Queue()
    unbounded.create_stream_local("1", queue_response=responses)
    unbounded.ingest_local("1", {"x": 0}, queue_response=responses)
    runtime.run(timeout=1.5)
    assert unbounded.streams["1"].in_flight == 1     # still parked
    assert responses.empty()
    unbounded.stop()


# -- (c) overload shedding ---------------------------------------------------


def test_overload_sheds_with_inorder_delivery(runtime):
    """2x overload on a live stream with shed_oldest: some frames shed
    (counted, error-responded), the rest complete, delivery order is
    ingest order, nothing hangs."""
    pipeline = Pipeline(
        {"version": 0, "name": "p_shed", "runtime": "jax",
         "graph": ["(det llm)"],
         "parameters": {"overload_policy": "shed_oldest",
                        "overload_limit": 3,
                        "stage_inflight": 1},
         "elements": [
             element("det", "BusyStage", parameters={"busy_ms": 25.0},
                     placement={"devices": 4}),
             element("llm", "BusyStage", parameters={"busy_ms": 25.0},
                     placement={"devices": 4})]},
        runtime=runtime)
    responses = queue.Queue()
    n_frames = 12
    ingest(pipeline, responses, n_frames)
    rows = collect(runtime, responses, n_frames)
    assert len(rows) == n_frames, "responses lost under shedding"
    shed = [row for row in rows if not row[4]]
    okay = [row for row in rows if row[4]]
    assert pipeline.share["frames_shed"] > 0
    assert len(shed) == pipeline.share["frames_shed"]
    assert all("shed: overload" in row[5] for row in shed)
    assert okay, "everything shed: limit too tight"
    # In-order delivery preserved across sheds.
    order = [row[1] for row in rows]
    assert order == sorted(order)
    assert "0" in pipeline.streams          # shed never ERRORs a stream
    pipeline.stop()


def test_shed_newest_refuses_incoming(runtime):
    pipeline = Pipeline(
        {"version": 0, "name": "p_shed_new", "runtime": "jax",
         "graph": ["(echo)"],
         "parameters": {"overload_policy": "shed_newest",
                        "overload_limit": 2},
         "elements": [element("echo", "SlowAsyncEcho",
                              parameters={"delay_ms": 80.0})]},
        runtime=runtime)
    responses = queue.Queue()
    ingest(pipeline, responses, 6)
    rows = collect(runtime, responses, 6)
    assert len(rows) == 6
    shed = [row for row in rows if not row[4]]
    assert shed and all("shed: overload" in row[5] for row in shed)
    assert pipeline.share["frames_shed"] == len(shed)
    assert len(rows) - len(shed) >= 2
    pipeline.stop()


# -- deadlines ---------------------------------------------------------------


def test_deadline_fails_parked_frame_without_killing_stream(runtime):
    """A frame parked at a stage that never answers in time errors at
    its deadline; the stream survives and later frames complete."""
    pipeline = Pipeline(
        {"version": 0, "name": "p_deadline", "runtime": "jax",
         "graph": ["(echo)"],
         "parameters": {"frame_deadline_ms": 60},
         "elements": [element("echo", "SlowAsyncEcho",
                              parameters={"delay_ms": 500.0})]},
        runtime=runtime)
    responses = queue.Queue()
    ingest(pipeline, responses, 1)
    start = time.monotonic()
    rows = collect(runtime, responses, 1, timeout=10.0)
    elapsed = time.monotonic() - start
    assert rows and not rows[0][4]
    assert "deadline exceeded" in rows[0][5]
    assert elapsed < 0.45, "deadline error arrived after the work"
    assert pipeline.share["deadline_misses"] == 1
    assert "0" in pipeline.streams           # stream survived the miss

    # Stream still serves: a fast frame completes fine.
    pipeline.graph.get_node("echo").element.set_parameter(
        "delay_ms", 1.0)
    ingest(pipeline, responses, 1)
    rows = collect(runtime, responses, 1, timeout=10.0)
    assert rows and rows[0][4], rows[0][5]
    pipeline.stop()


# -- satellites: probe timeout, stall, live arm/disarm -----------------------


def test_health_probe_timeout_parameter_plumbs_through(runtime):
    """The ``health_probe_timeout`` pipeline parameter bounds a hung
    prober (device_hang injection) instead of the hardcoded 5 s."""
    pipeline = Pipeline(
        {"version": 0, "name": "p_timeout", "runtime": "jax",
         "graph": ["(sq)"],
         "parameters": {"health_probe_timeout": 0.2},
         "elements": [element("sq", "BusyStage",
                              parameters={"busy_ms": 0.0},
                              placement={"mesh": {"dp": 8}})]},
        runtime=runtime)
    pipeline.arm_faults({"rules": [
        {"point": "device_hang", "target": "device:0", "count": 1,
         "delay_ms": 3000.0}]})
    start = time.perf_counter()
    failed = pipeline.check_device_health()
    elapsed = time.perf_counter() - start
    assert len(failed) == 1                 # hung chip counted as dead
    assert elapsed < 2.0, "probe ignored health_probe_timeout"
    assert pipeline.stage_placement.generation == 1
    pipeline.stop()


def test_stage_stall_delays_but_preserves_order(runtime):
    """stage_stall occupies one stage's FIFO worker; queued frames wait
    behind the stall and still deliver in order."""
    pipeline = Pipeline(
        {"version": 0, "name": "p_stall", "runtime": "jax",
         "graph": ["(det llm)"],
         "parameters": {"fault_plan": {"rules": [
             {"point": "stage_stall", "target": "llm", "count": 1,
              "delay_ms": 150.0}]}},
         "elements": [
             element("det", "BusyStage", parameters={"busy_ms": 2.0},
                     placement={"devices": 4}),
             element("llm", "BusyStage", parameters={"busy_ms": 2.0},
                     placement={"devices": 4})]},
        runtime=runtime)
    responses = queue.Queue()
    start = time.perf_counter()
    ingest(pipeline, responses, 4)
    rows = collect(runtime, responses, 4)
    elapsed = time.perf_counter() - start
    assert len(rows) == 4 and all(row[4] for row in rows)
    assert elapsed > 0.14, "stall never hit the worker"
    assert [row[1] for row in rows] == sorted(row[1] for row in rows)
    assert pipeline.fault_stats()["plan"]["fired"]["stage_stall"] == 1
    pipeline.stop()


def test_live_arm_and_disarm_via_set_parameter(runtime):
    """The dashboard path: ``set_parameter fault_plan <json>`` arms a
    running pipeline; an empty value disarms."""
    pipeline = Pipeline(
        {"version": 0, "name": "p_live", "runtime": "jax",
         "graph": ["(inc)"],
         "elements": [
             {"name": "inc", "input": [{"name": "x"}],
              "output": [{"name": "x"}],
              "deploy": {"local": {
                  "module": "aiko_services_tpu.elements.common",
                  "class_name": "Increment"}}}]},
        runtime=runtime)
    pipeline.set_parameter(
        "fault_plan",
        '{"rules": [{"point": "element_raise", "target": "inc", '
        '"count": 1}]}')
    assert pipeline.share["faults_armed"] is True
    responses = queue.Queue()
    pipeline.create_stream_local("a", queue_response=responses)
    pipeline.ingest_local("a", {"x": 1}, queue_response=responses)
    rows = collect(runtime, responses, 1)
    assert rows and not rows[0][4]          # unplaced: no replay path
    assert "injected device failure" in rows[0][5]
    pipeline.set_parameter("fault_plan", "off")
    assert pipeline.share["faults_armed"] is False
    assert pipeline.fault_stats()["armed"] is False
    pipeline.stop()


def test_fallback_definition_validation():
    from aiko_services_tpu.pipeline.definition import (
        DefinitionError, parse_pipeline_definition)

    base = {"version": 0, "name": "p", "runtime": "jax",
            "graph": ["(fwd)"],
            "elements": [
                {"name": "fwd", "input": [], "output": [],
                 "deploy": {"remote": {"name": "back"}},
                 "fallback": "missing"}]}
    with pytest.raises(DefinitionError, match="not a defined element"):
        parse_pipeline_definition(base)
    local = {"version": 0, "name": "p", "runtime": "jax",
             "graph": ["(a)"],
             "elements": [
                 {"name": "a", "input": [], "output": [],
                  "deploy": {"local": {"module": "m",
                                       "class_name": "C"}},
                  "fallback": "a"}]}
    with pytest.raises(DefinitionError, match="remote-deployed"):
        parse_pipeline_definition(local)


def test_device_window_invalidate_drops_dead_leaves():
    from aiko_services_tpu.pipeline.overlap import DeviceWindow

    devices = jax.devices()
    window = DeviceWindow()
    alive = jax.device_put(np.ones(4, np.float32), devices[1])
    doomed = jax.device_put(np.ones(4, np.float32), devices[0])
    window.note(0, {"x": doomed})
    window.note(1, {"x": alive})
    assert window.outstanding == 2
    assert window.invalidate({devices[0]}) == 1
    assert window.outstanding == 1
    window.drain()                          # survivor still paceable


# -- replicated stages: replica death under load (ISSUE 7) -------------------


def replicated_chaos_definition(parameters=None):
    """detect at ``replicas: 3`` (2 chips each) feeding an unreplicated
    placed llm -- the BENCH e2e shape, 8 chips total on the CPU mesh."""
    return {
        "version": 0, "name": "p_replica_chaos", "runtime": "jax",
        "graph": ["(detect llm)"],
        "parameters": dict(parameters or {}),
        "elements": [
            element("detect", "BusyStage",
                    parameters={"busy_ms": 25.0},
                    placement={"devices": 2, "replicas": 3}),
            element("llm", "BusyStage", parameters={"busy_ms": 5.0},
                    placement={"devices": 2})]}


def test_replica_device_kill_sheds_to_peers_in_order_under_load(runtime):
    """The ISSUE 7 acceptance walk: detect at ``replicas: 3``, a
    ``device_kill`` rule targeting ONE replica (``detect#1``) fires
    under >= 12 in-flight frames across two streams.  Every stream
    completes -- zero dropped, zero duplicated, in ingest order per
    stream -- the group keeps serving at N-1 (no generation bump, the
    peer-shed path, NOT stop-the-world replace), and the dead slot
    shows on the telemetry gauges."""
    pipeline = Pipeline(
        replicated_chaos_definition(parameters={
            "replay_limit": 3,
            "replica_rebuild_ms": 0,        # hold the N-1 state
            "telemetry": "on",
            "health_probe_timeout": 2.0,
            "fault_plan": {"rules": [
                {"point": "device_kill", "target": "detect#1",
                 "count": 1}]}}),
        runtime=runtime)
    n_frames = 7
    responses_a: queue.Queue = queue.Queue()
    responses_b: queue.Queue = queue.Queue()
    ingest(pipeline, responses_a, n_frames, stream_id="a")
    ingest(pipeline, responses_b, n_frames, stream_id="b")

    # Wait until replica 1 actually holds admitted frames, then run the
    # health probe: the armed rule marks exactly that submesh dead.
    def replica1_busy():
        return any(frame.stage == "detect" and frame.stage_replica == 1
                   for stream in pipeline.streams.values()
                   for frame in stream.frames.values())

    assert run_until(runtime, replica1_busy, timeout=30.0), \
        "no frame ever admitted to replica 1"
    in_flight = sum(len(stream.frames)
                    for stream in pipeline.streams.values())
    assert in_flight >= 12, f"only {in_flight} frames in flight"
    pipeline.post_self("check_device_health")
    rows_a = collect(runtime, responses_a, n_frames, timeout=120.0)
    rows_b = collect(runtime, responses_b, n_frames, timeout=120.0)
    for rows in (rows_a, rows_b):
        assert len(rows) == n_frames, \
            f"{len(rows)}/{n_frames}: dropped frames after replica kill"
        assert all(row[4] for row in rows), \
            [row[5] for row in rows if not row[4]]
        order = [row[1] for row in rows]
        assert order == sorted(order), f"out of order: {order}"
        assert len(order) == len(set(order)), "duplicate delivery"
    # Peer-shed semantics: generation unchanged, peers alive at N-1,
    # the dead replica's in-flight frames replayed.
    placement = pipeline.stage_placement
    assert placement.generation == 0, "failover escalated to replace()"
    assert placement.live_replicas("detect") == [0, 2]
    assert pipeline.share["replica_failovers"] == 1
    assert pipeline.share["replica_failover_ms"] > 0
    assert pipeline.share["frames_replayed"] > 0
    assert pipeline.fault_stats()["plan"]["fired"] == {"device_kill": 1}
    # Scrape-side view: the dead slot reads 0 on the replica_state
    # gauge while its peers read 1.
    states = {}
    for line in pipeline.metrics_text().splitlines():
        if line.startswith("aiko_replica_state{"):
            states[line] = line.rsplit(" ", 1)[1]
    assert sorted(states.values()) == ["0", "1", "1"], states
    stats = pipeline.replica_stats()
    assert stats["stages"]["detect"]["states"] == \
        ["live", "dead", "live"]
    pipeline.stop()


def test_replica_failover_strictly_cheaper_than_full_replace(runtime):
    """The robustness dividend, measured: peer-shedding one dead
    replica (``replica_failover_ms``) is strictly cheaper than the
    stop-the-world ``replace_failed_devices`` rebuild under comparable
    in-flight load -- failover touches ONE submesh, replace re-carves
    every stage and replays everything."""
    pipeline = Pipeline(
        replicated_chaos_definition(parameters={
            "replay_limit": 4, "replica_rebuild_ms": 0}),
        runtime=runtime)
    placement = pipeline.stage_placement
    n_frames = 8
    responses: queue.Queue = queue.Queue()
    ingest(pipeline, responses, n_frames, stream_id="a")

    def detect_busy():
        return sum(1 for stream in pipeline.streams.values()
                   for frame in stream.frames.values()
                   if frame.stage == "detect") >= 2

    assert run_until(runtime, detect_busy, timeout=30.0)
    pipeline.fail_replica("detect", 1)
    failover_ms = pipeline.share["replica_failover_ms"]
    rows = collect(runtime, responses, n_frames, timeout=120.0)
    assert all(row[4] for row in rows), \
        [row[5] for row in rows if not row[4]]

    # Same pipeline, comparable load: now kill the llm stage's chips --
    # outside any replica, so recovery MUST stop the world.
    responses = queue.Queue()
    ingest(pipeline, responses, n_frames, stream_id="b")

    def llm_busy():
        return sum(1 for stream in pipeline.streams.values()
                   for frame in stream.frames.values()) >= 2

    assert run_until(runtime, llm_busy, timeout=30.0)
    dead = list(placement.plans["llm"].mesh.devices.flat)[:1]
    start = time.perf_counter()
    pipeline.replace_failed_devices(dead)
    replace_ms = (time.perf_counter() - start) * 1000.0
    rows = collect(runtime, responses, n_frames, timeout=120.0)
    assert all(row[4] for row in rows), \
        [row[5] for row in rows if not row[4]]
    assert placement.generation == 1
    assert failover_ms < replace_ms, (
        f"peer-shed failover ({failover_ms:.2f} ms) not cheaper than "
        f"full replace ({replace_ms:.2f} ms)")
    pipeline.stop()


def test_replica_scoped_dispatch_probe_spares_healthy_peers(runtime):
    """Dispatch-time chip death on a replicated stage: the raising
    frame's probe is SCOPED to its own replica's submesh, so the armed
    ``device_kill`` confirms THAT replica dead and the peers never get
    probed, marked, or replayed -- one slot fails, N-1 serve on,
    generation unchanged."""
    pipeline = Pipeline(
        replicated_chaos_definition(parameters={
            "replay_limit": 3,
            "replica_rebuild_ms": 0,
            "health_probe_timeout": 2.0,
            "fault_plan": {"rules": [
                # The FIRST detect dispatch raises; round-robin admits
                # frame 0 to replica 0, so the scoped probe walks
                # replica 0's chips and finds them dead.
                {"point": "element_raise", "target": "detect",
                 "count": 1},
                {"point": "device_kill", "target": "detect#0",
                 "count": 1}]}}),
        runtime=runtime)
    n_frames = 4
    responses: queue.Queue = queue.Queue()
    ingest(pipeline, responses, n_frames)
    rows = collect(runtime, responses, n_frames, timeout=120.0)
    assert len(rows) == n_frames
    assert all(row[4] for row in rows), \
        [row[5] for row in rows if not row[4]]
    placement = pipeline.stage_placement
    assert placement.generation == 0, \
        "scoped probe escalated to a full replace"
    assert placement.live_replicas("detect") == [1, 2]
    assert pipeline.share["replica_failovers"] == 1
    assert pipeline.share["frames_replayed"] >= 1
    fired = pipeline.fault_stats()["plan"]["fired"]
    assert fired == {"element_raise": 1, "device_kill": 1}
    pipeline.stop()


def test_decode_block_kill_replays_generation_from_last_block(runtime):
    """ISSUE 8 satellite: a ``decode_block`` device_kill firing
    MID-GENERATION (after the first loop block retired, so tokens are
    already committed) replays every live request from its last
    emitted block -- the frame completes with text IDENTICAL to an
    unfaulted run (nothing lost, nothing re-emitted), one recovery."""
    def llm_pipeline(name, fault_rules):
        parameters = {}
        if fault_rules:
            parameters["fault_plan"] = {"rules": fault_rules}
        return Pipeline(
            {"version": 0, "name": name, "runtime": "jax",
             "parameters": parameters,
             "graph": ["(llm)"],
             "elements": [{
                 "name": "llm",
                 "input": [{"name": "text"}],
                 "output": [{"name": "text"}],
                 # inflight 1: each step dispatches one block (one
                 # probe) and retires it, so ``after: 1`` fires with
                 # block 1's tokens already emitted.
                 "parameters": {"max_new_tokens": 12, "max_seq": 64,
                                "decode_block_tokens": 4, "inflight": 1},
                 "deploy": {"local": {
                     "module": "aiko_services_tpu.elements.llm",
                     "class_name": "LLM"}}}]},
            runtime=runtime)

    def generate(pipeline):
        responses: queue.Queue = queue.Queue()
        stream = pipeline.create_stream_local(
            "s", queue_response=responses)
        pipeline.create_frame_local(stream, {"text": "chaos prompt"})
        assert run_until(runtime, lambda: not responses.empty(),
                         timeout=120.0)
        _, _, swag, _, okay, diagnostic = responses.get()
        assert okay, diagnostic
        return swag["text"]

    reference_pipe = llm_pipeline("llm_ref", None)
    reference = generate(reference_pipe)
    reference_pipe.stop()

    pipeline = llm_pipeline("llm_chaos", [
        {"point": "decode_block", "target": "llm", "after": 1,
         "count": 1}])
    text = generate(pipeline)
    assert text == reference, "replayed generation diverged"
    batcher = pipeline.graph.get_node("llm").element._batcher
    assert batcher.recoveries == 1
    assert pipeline.fault_stats()["plan"]["fired"] == {"decode_block": 1}
    pipeline.stop()


def test_decode_block_hang_delays_but_completes(runtime):
    """A ``decode_block`` rule WITH delay_ms hangs one dispatch; the
    generation still completes (no recovery fired -- a hang is not a
    death)."""
    pipeline = Pipeline(
        {"version": 0, "name": "llm_hang", "runtime": "jax",
         "parameters": {"fault_plan": {"rules": [
             {"point": "decode_block", "target": "llm", "count": 1,
              "delay_ms": 150}]}},
         "graph": ["(llm)"],
         "elements": [{
             "name": "llm",
             "input": [{"name": "text"}],
             "output": [{"name": "text"}],
             "parameters": {"max_new_tokens": 6, "max_seq": 64,
                            "decode_block_tokens": 4},
             "deploy": {"local": {
                 "module": "aiko_services_tpu.elements.llm",
                 "class_name": "LLM"}}}]},
        runtime=runtime)
    responses: queue.Queue = queue.Queue()
    stream = pipeline.create_stream_local("s", queue_response=responses)
    pipeline.create_frame_local(stream, {"text": "hang on"})
    assert run_until(runtime, lambda: not responses.empty(),
                     timeout=120.0)
    _, _, swag, _, okay, diagnostic = responses.get()
    assert okay, diagnostic
    assert isinstance(swag["text"], str)
    batcher = pipeline.graph.get_node("llm").element._batcher
    assert batcher.recoveries == 0
    assert pipeline.fault_stats()["plan"]["fired"] == {"decode_block": 1}
    pipeline.stop()


# -- (e) wire-fault parity on the tensor-pipe data plane (ISSUE 9) -----------
#
# The control envelope still rides MQTT when tensors take the pipe, so
# every ``wire_*`` rule must fire on a pipe-data-plane pipeline with
# the SAME blast radius and the SAME recovery (deadline -> breaker ->
# reclose; dup discard) the MQTT path shows -- chaos coverage must not
# narrow when the data moves off the broker.


def _pipe_remote_pair(runtime, **front_params):
    Registrar(runtime=runtime, primary_search_timeout=0.05)
    back = Pipeline(
        {"version": 0, "name": "back", "runtime": "jax",
         "graph": ["(inc)"],
         "elements": [element("inc", "Identity",
                              module="aiko_services_tpu.elements"
                                     ".common")]},
        runtime=runtime)
    front = Pipeline(
        {"version": 0, "name": "front", "runtime": "jax",
         "graph": ["(fwd)"],
         "parameters": {"frame_deadline_ms": 400,
                        "breaker_threshold": 2,
                        "breaker_cooldown_ms": 250, **front_params},
         "elements": [
             {"name": "fwd", "input": [{"name": "x"}],
              "output": [{"name": "x"}],
              "deploy": {"remote": {"name": "back"}}}]},
        runtime=runtime)
    stage = front.graph.get_node("fwd").element
    assert run_until(runtime,
                     lambda: stage.remote_topic_path is not None,
                     timeout=10.0)
    assert stage.remote_pipe is not None      # pipe negotiated
    return front, back


def test_wire_drop_parity_on_tensor_pipe_path(runtime):
    """wire_drop of responses on a PIPE-data-plane pipeline: the exact
    MQTT-path walk -- two deadline misses open the breaker, fail-fast,
    half-open probe recloses once the wire heals -- with tensors
    verifiably riding the pipe and EXACTLY two rule firings."""
    front, back = _pipe_remote_pair(runtime)
    responses = queue.Queue()
    x = np.arange(4096, dtype=np.float32)
    front.create_stream_local("w", {"frame_deadline_ms": 0},
                              queue_response=responses)
    front.ingest_local("w", {"x": x}, queue_response=responses)
    warm = collect(runtime, responses, 1)
    assert warm and warm[0][4], warm[0]
    assert front.data_plane_stats()["pipe_frames"] >= 1
    front.create_stream_local("1", queue_response=responses)

    front.arm_faults({"rules": [
        {"point": "wire_drop", "target": "process_frame_response",
         "count": 2}]})
    for _ in range(2):
        front.ingest_local("1", {"x": x}, queue_response=responses)
        rows = collect(runtime, responses, 1, timeout=10.0)
        assert rows and not rows[0][4]
        assert "deadline" in rows[0][5]
    breaker = front.breakers["fwd"]
    assert breaker.state == BREAKER_OPEN
    assert front.share["deadline_misses"] == 2

    front.ingest_local("1", {"x": x}, queue_response=responses)
    rows = collect(runtime, responses, 1, timeout=10.0)
    assert rows and not rows[0][4]
    assert "circuit breaker open" in rows[0][5]
    assert "1" in front.streams                  # stream alive

    time.sleep(0.3)
    front.ingest_local("1", {"x": x}, queue_response=responses)
    rows = collect(runtime, responses, 1, timeout=10.0)
    assert rows and rows[0][4], rows[0][5]
    np.testing.assert_array_equal(np.asarray(rows[0][2]["x"]), x)
    assert breaker.state == BREAKER_CLOSED
    assert [s for s, _ in breaker.transitions] == \
        ["open", "half_open", "closed"]
    # Exact blast radius, via the plan trace -- identical to MQTT.
    plan = front.fault_stats()["plan"]
    assert plan["fired"]["wire_drop"] == 2
    assert len([t for t in plan["trace"]
                if t["point"] == "wire_drop"]) == 2
    # The recovered frames still used the pipe for their tensors.
    assert front.data_plane_stats()["pipe_frames"] >= 3
    front.stop()
    back.stop()


def test_wire_corrupt_and_dup_parity_on_tensor_pipe_path(runtime):
    """wire_corrupt of a process_frame envelope on the pipe path: the
    receiver's parse drops it (same as MQTT), the parked frame
    deadline-fails without killing the stream, the next frame flows.
    wire_dup of a response: the duplicate is discarded once the frame
    moved on -- one delivery, correct value."""
    front, back = _pipe_remote_pair(runtime)
    responses = queue.Queue()
    x = np.arange(1024, dtype=np.int32)
    front.create_stream_local("w", {"frame_deadline_ms": 0},
                              queue_response=responses)
    front.ingest_local("w", {"x": x}, queue_response=responses)
    warm = collect(runtime, responses, 1)
    assert warm and warm[0][4], warm[0]

    front.create_stream_local("1", queue_response=responses)
    front.arm_faults({"rules": [
        {"point": "wire_corrupt", "target": "process_frame",
         "count": 1}]})
    front.ingest_local("1", {"x": x}, queue_response=responses)
    rows = collect(runtime, responses, 1, timeout=10.0)
    assert rows and not rows[0][4]
    assert "deadline" in rows[0][5]
    assert "1" in front.streams                  # stream alive
    front.ingest_local("1", {"x": x}, queue_response=responses)
    rows = collect(runtime, responses, 1, timeout=10.0)
    assert rows and rows[0][4], rows[0][5]

    front.arm_faults({"rules": [
        {"point": "wire_dup", "target": "process_frame_response",
         "count": 1}]})
    front.ingest_local("1", {"x": x}, queue_response=responses)
    rows = collect(runtime, responses, 2, timeout=5.0)
    assert len(rows) == 1                        # duplicate discarded
    assert rows[0][4], rows[0][5]
    np.testing.assert_array_equal(np.asarray(rows[0][2]["x"]), x)
    plan = front.fault_stats()["plan"]
    assert plan["fired"] == {"wire_dup": 1}      # re-armed plan
    front.stop()
    back.stop()
