"""Distributed frame tracing across RemoteStage hops (ISSUE 4): the
trace context survives park/forward/resume round trips (including the
undiscovered-remote retry/backoff path), and a two-stage PLACED
pipeline with a remote hop yields ONE reconstructed trace -- a single
trace_id with spans from both processes -- while ``metrics_text()``
exposes nonzero p50/p99 for every element and stage."""

import queue

from conftest import run_until

from aiko_services_tpu.pipeline import Pipeline
from aiko_services_tpu.services import Registrar

COMMON = "aiko_services_tpu.elements.common"


def element(name, cls, parameters=None, placement=None, module=COMMON):
    definition = {"name": name, "input": [{"name": "x"}],
                  "output": [{"name": "x"}],
                  "deploy": {"local": {"module": module,
                                       "class_name": cls}},
                  "parameters": parameters or {}}
    if placement:
        definition["placement"] = placement
    return definition


def remote(name, target):
    return {"name": name, "input": [{"name": "x"}],
            "output": [{"name": "x"}],
            "deploy": {"remote": {"name": target}}}


def back_pipeline(runtime, name="back", cls="Increment"):
    return Pipeline({"version": 0, "name": name, "runtime": "jax",
                     "graph": ["(inc)"],
                     "elements": [element("inc", cls)]},
                    runtime=runtime)


def await_discovery(runtime, front, stage_name, timeout=10.0):
    stage = front.graph.get_node(stage_name).element
    assert run_until(runtime,
                     lambda: stage.remote_topic_path is not None,
                     timeout=timeout)


def test_trace_spans_both_processes(runtime):
    """Round trip: origin's TraceBuffer holds one trace whose spans
    cover both pipelines, parented under the hop span."""
    Registrar(runtime=runtime, primary_search_timeout=0.05)
    back = back_pipeline(runtime)
    front = Pipeline({"version": 0, "name": "front", "runtime": "jax",
                      "graph": ["(inc (fwd))"],
                      "elements": [element("inc", "Increment"),
                                   remote("fwd", "back")]},
                     runtime=runtime)
    await_discovery(runtime, front, "fwd")
    responses = queue.Queue()
    front.process_frame_local({"x": 0}, stream_id="s",
                              queue_response=responses)
    assert run_until(runtime, lambda: not responses.empty(),
                     timeout=10.0)
    *_, okay, diagnostic = responses.get()
    assert okay, diagnostic

    trace = front.telemetry.traces.recent(1)[0]
    spans = trace["spans"]
    assert {span["trace_id"] for span in spans} == {trace["trace_id"]}
    assert {span["process"] for span in spans} == {"front", "back"}
    names = {span["name"] for span in spans}
    assert {"element:inc", "remote:fwd"} <= names
    # The remote pipeline's root span is parented under the hop span.
    hop = next(s for s in spans if s["name"] == "remote:fwd")
    remote_root = next(s for s in spans if s["kind"] == "frame"
                       and s["process"] == "back")
    assert remote_root["parent_id"] == hop["span_id"]
    # The remote pipeline's own buffer holds its local view of the
    # SAME trace id.
    assert back.telemetry.traces.get(trace["trace_id"]) is not None
    front.stop()
    back.stop()


def test_trace_id_survives_remote_retry_backoff(runtime):
    """A frame parked waiting for remote discovery retries with
    exponential backoff (remote_stage_retries) -- and resumes with the
    SAME trace_id, so the slow discovery is one long trace, not a
    broken one."""
    Registrar(runtime=runtime, primary_search_timeout=0.05)
    front = Pipeline({"version": 0, "name": "front", "runtime": "jax",
                      "graph": ["(inc (fwd))"],
                      "elements": [element("inc", "Increment"),
                                   remote("fwd", "back")]},
                     runtime=runtime)
    responses = queue.Queue()
    front.create_stream_local("s", queue_response=responses)
    front.ingest_local("s", {"x": 0}, queue_response=responses)
    runtime.run(timeout=0.7)               # several backoff cycles
    frame = front.streams["s"].frames[0]
    minted = frame.trace_id
    assert minted is not None
    assert frame.remote_retries > 0
    assert front.share["remote_stage_retries"] > 0

    back = back_pipeline(runtime)          # NOW the remote appears
    assert run_until(runtime, lambda: not responses.empty(),
                     timeout=10.0)
    *_, okay, diagnostic = responses.get()
    assert okay, diagnostic
    trace = front.telemetry.traces.get(minted)
    assert trace is not None, "trace_id changed across retries"
    assert {span["process"] for span in trace["spans"]} == \
        {"front", "back"}
    # The retry count also reached the telemetry counters.
    assert front.telemetry.rollup()["counters"][
        "remote_stage_retries"] >= frame.remote_retries
    front.stop()
    back.stop()


def test_placed_two_stage_remote_hop_acceptance(runtime):
    """ISSUE 4 acceptance: a two-stage PLACED pipeline with a
    RemoteStage hop yields a single reconstructed trace (one trace_id,
    >= 4 spans spanning both processes) from the TraceBuffer, and
    metrics_text() exposes nonzero p50/p99 latency for every
    element/stage under sustained frames."""
    import jax

    assert len(jax.devices()) >= 2
    n = len(jax.devices())
    Registrar(runtime=runtime, primary_search_timeout=0.05)
    back = back_pipeline(runtime, cls="Identity")  # array-safe remote
    front = Pipeline({
        "version": 0, "name": "front", "runtime": "jax",
        "graph": ["(detect (llm (fwd)))"],
        "parameters": {"telemetry_interval": 0.0},
        "elements": [
            element("detect", "StageWork", {"busy_ms": 2.0,
                                            "factor": 2.0},
                    {"devices": n // 2}),
            element("llm", "StageWork", {"busy_ms": 3.0, "factor": 3.0},
                    {"devices": n - n // 2}),
            remote("fwd", "back"),
        ]}, runtime=runtime)
    assert front.stage_scheduler is not None     # stage-parallel active
    await_discovery(runtime, front, "fwd")

    import numpy as np
    frames = 10
    responses = queue.Queue()
    x = np.ones((16, 16), dtype=np.float32)
    for _ in range(frames):
        front.process_frame_local({"x": x}, stream_id="s",
                                  queue_response=responses)
    assert run_until(runtime, lambda: responses.qsize() >= frames,
                     timeout=60.0)
    rows = [responses.get() for _ in range(frames)]
    assert all(row[4] for row in rows), rows[0][5]

    # -- one reconstructed trace, >= 4 spans, both processes ---------------
    trace = front.telemetry.traces.recent(1)[0]
    spans = trace["spans"]
    assert len(spans) >= 4
    assert {span["trace_id"] for span in spans} == {trace["trace_id"]}
    assert {span["process"] for span in spans} == {"front", "back"}
    kinds = {span["kind"] for span in spans}
    assert {"element", "stage", "remote", "frame"} <= kinds

    # -- nonzero p50/p99 for every element and stage -----------------------
    text = front.metrics_text()
    lines = text.splitlines()
    for label, names in (("element", ("detect", "llm")),
                         ("stage", ("detect", "llm"))):
        series = "element_latency_ms" if label == "element" \
            else "stage_latency_ms"
        for name in names:
            for q in ("0.5", "0.99"):
                prefix = (f'aiko_{series}{{{label}="{name}"'
                          f',quantile="{q}"}}')
                line = next((l for l in lines if l.startswith(prefix)),
                            None)
                assert line is not None, f"missing {prefix}"
                assert float(line.split()[-1]) > 0.0, line
    # remote element's quantiles live in the BACK pipeline's exposition
    back_text = back.metrics_text()
    assert 'aiko_element_latency_ms{element="inc",quantile="0.99"}' in \
        back_text
    front.stop()
    back.stop()
