"""The example scripts actually run: each aloha_honua demo (minimal
actor, discovery/do_command, do_request) executes as a subprocess and
produces its expected output -- examples are living documentation of the
actor / discovery / request-response patterns (reference
examples/aloha_honua/aloha_honua_{0..3}.py)."""

import pathlib
import subprocess
import sys

import pytest

EXAMPLES = pathlib.Path(__file__).resolve().parent.parent / "examples"


SANDBOX_ENV = {"PATH": "/usr/bin:/bin", "AIKO_LOG_LEVEL": "ERROR",
               "JAX_PLATFORMS": "cpu", "HOME": "/tmp"}


def run_example(relative, timeout=300, force_cpu=False):
    """Run an example as a subprocess.  ``force_cpu`` additionally pins
    the JAX backend programmatically before the script body: a site
    hook may import jax at interpreter start and override the
    JAX_PLATFORMS env var, which would send example tests to remote
    hardware."""
    path = str(EXAMPLES / relative)
    if force_cpu:
        bootstrap = (
            "import jax, sys\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            f"path = {path!r}\n"
            "sys.argv = [path]\n"
            "exec(compile(open(path).read(), path, 'exec'),"
            " {'__name__': '__main__', '__file__': path})\n")
        command = [sys.executable, "-c", bootstrap]
    else:
        command = [sys.executable, path]
    result = subprocess.run(command, capture_output=True, text=True,
                            timeout=timeout, env=dict(SANDBOX_ENV))
    assert result.returncode == 0, result.stderr[-2000:]
    return result.stdout


@pytest.mark.parametrize("script,expected", [
    ("aloha_honua/aloha_honua_0.py", "Aloha Pele!"),
    ("aloha_honua/aloha_honua_1.py", "Aloha Honua!"),
    ("aloha_honua/aloha_honua_2.py", "response:"),
    ("robot/run_ooda.py", "last_action=sit"),
])
def test_aloha_example(script, expected):
    stdout = run_example(script)
    assert expected in stdout, stdout


@pytest.mark.parametrize("script,expected", [
    ("pipeline/run_local.py", "result="),
    ("pipeline/run_paths.py", "path in_square: x=6 -> result=36"),
    ("pipeline/run_remote.py", "worker added 100"),
    ("detector/detect_image.py", "detections:"),
    ("llm/chat.py", "DONE"),
    ("speech/run_speech.py", "reply.wav"),
])
def test_model_example(script, expected):
    """Every model-path demo runs end to end (CPU backend): these are
    the reference's yolo/llm/speech example equivalents and break
    silently when element contracts drift -- detect_image.py's missing
    'path' input went unnoticed exactly this way."""
    stdout = run_example(script, force_cpu=True)
    assert expected in stdout, stdout
