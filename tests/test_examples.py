"""The example scripts actually run: each aloha_honua demo (minimal
actor, discovery/do_command, do_request) executes as a subprocess and
produces its expected output -- examples are living documentation of the
actor / discovery / request-response patterns (reference
examples/aloha_honua/aloha_honua_{0..3}.py)."""

import pathlib
import subprocess
import sys

import pytest

EXAMPLES = pathlib.Path(__file__).resolve().parent.parent / "examples"


def run_example(relative, timeout=60):
    result = subprocess.run(
        [sys.executable, str(EXAMPLES / relative)],
        capture_output=True, text=True, timeout=timeout,
        env={"PATH": "/usr/bin:/bin", "AIKO_LOG_LEVEL": "ERROR",
             "JAX_PLATFORMS": "cpu", "HOME": "/tmp"})
    assert result.returncode == 0, result.stderr[-2000:]
    return result.stdout


@pytest.mark.parametrize("script,expected", [
    ("aloha_honua/aloha_honua_0.py", "Aloha Pele!"),
    ("aloha_honua/aloha_honua_1.py", "Aloha Honua!"),
    ("aloha_honua/aloha_honua_2.py", "response:"),
    ("robot/run_ooda.py", "last_action=sit"),
])
def test_aloha_example(script, expected):
    stdout = run_example(script)
    assert expected in stdout, stdout
