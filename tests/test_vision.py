"""Face + ArUco detector elements (reference examples/face/face.py:52,
examples/aruco_marker/aruco.py:80,136) running through real pipelines."""

import queue

import numpy as np
import pytest

from conftest import run_until
from aiko_services_tpu.pipeline import Pipeline
from test_media import definition, element

cv2 = pytest.importorskip("cv2")


def run_frame(runtime, pipeline, frame_data, timeout=10.0):
    responses = queue.Queue()
    pipeline.process_frame_local(frame_data, queue_response=responses)
    assert run_until(runtime, lambda: not responses.empty(),
                     timeout=timeout)
    _, _, swag, _, okay, diagnostic = responses.get()
    return swag, okay, diagnostic


def aruco_scene(marker_id=7, tags="DICT_4X4_50", size=64, pad=24):
    """A real rendered ArUco marker pasted on a white background."""
    dictionary = cv2.aruco.getPredefinedDictionary(
        getattr(cv2.aruco, tags))
    marker = cv2.aruco.generateImageMarker(dictionary, marker_id, size)
    canvas = np.full((size + 2 * pad, size + 2 * pad), 255, np.uint8)
    canvas[pad:pad + size, pad:pad + size] = marker
    return np.repeat(canvas[:, :, None], 3, axis=2)    # RGB


def test_aruco_detects_rendered_marker(runtime):
    pipeline = Pipeline(definition(
        ["(Aruco)"],
        [element("Aruco", "ArucoMarkerDetect", ["image"],
                 ["image", "overlay", "markers"])],
        name="p_aruco"), runtime=runtime)
    swag, okay, diagnostic = run_frame(runtime, pipeline,
                                       {"image": aruco_scene(7)})
    assert okay, diagnostic
    markers = swag["markers"]
    assert len(markers) == 1
    assert markers[0]["id"] == 7
    corners = np.asarray(markers[0]["corners"])
    assert corners.shape == (4, 2)
    # The marker sits at pad..pad+size in a 112px image.
    assert 16 <= corners[:, 0].min() <= 32
    rect = swag["overlay"]["rectangles"][0]
    assert rect["name"] == "aruco 7"
    assert 0.0 < rect["x"] < 1.0 and 0.0 < rect["w"] <= 1.0


def test_aruco_dictionary_parameter(runtime):
    """A 5x5 marker is invisible to a 4x4 detector and found by a 5x5
    detector selected via the aruco_tags parameter."""
    scene = aruco_scene(3, tags="DICT_5X5_50")
    p4 = Pipeline(definition(
        ["(Aruco)"],
        [element("Aruco", "ArucoMarkerDetect", ["image"], ["markers"])],
        name="p_aruco4"), runtime=runtime)
    swag, okay, _ = run_frame(runtime, p4, {"image": scene})
    assert okay and swag["markers"] == []

    p5 = Pipeline(definition(
        ["(Aruco)"],
        [element("Aruco", "ArucoMarkerDetect", ["image"], ["markers"],
                 {"aruco_tags": "DICT_5X5_50"})],
        name="p_aruco5"), runtime=runtime)
    swag, okay, _ = run_frame(runtime, p5, {"image": scene})
    assert okay and [m["id"] for m in swag["markers"]] == [3]


def test_aruco_unknown_dictionary_is_frame_error(runtime):
    pipeline = Pipeline(definition(
        ["(Aruco)"],
        [element("Aruco", "ArucoMarkerDetect", ["image"], ["markers"],
                 {"aruco_tags": "DICT_BOGUS"})],
        name="p_aruco_err"), runtime=runtime)
    _, okay, diagnostic = run_frame(runtime, pipeline,
                                    {"image": aruco_scene()})
    assert not okay
    assert "DICT_BOGUS" in diagnostic


def test_face_detect_blank_image(runtime):
    """With a Haar-cascade cv2 build a blank image yields the
    empty-but-well-formed output contract; on cascade-less cv2 5.x the
    element degrades to a per-frame diagnostic (not a crash)."""
    pipeline = Pipeline(definition(
        ["(Face)"],
        [element("Face", "FaceDetect", ["image"],
                 ["image", "overlay", "faces"])],
        name="p_face0"), runtime=runtime)
    image = np.full((60, 80, 3), 128, np.uint8)
    swag, okay, diagnostic = run_frame(runtime, pipeline, {"image": image})
    if hasattr(cv2, "CascadeClassifier"):
        assert okay, diagnostic
        assert swag["faces"] == []
        assert swag["overlay"] == {"rectangles": []}
    else:
        assert not okay
        assert "model" in diagnostic


def test_face_detect_reports_boxes_and_share_counter(runtime, monkeypatch):
    """Detection boxes surface as relative overlay rectangles and the
    cumulative count lands in the pipeline share dict (reference
    face.py: self.share['detections'])."""
    from aiko_services_tpu.elements import vision

    class FakeBackend:
        def detect(self, array):
            return np.array([[10, 5, 20, 30]])      # x y w h pixels

    monkeypatch.setattr(vision, "face_backend_factory",
                        lambda elem: FakeBackend())
    pipeline = Pipeline(definition(
        ["(Face Draw)"],
        [element("Face", "FaceDetect", ["image"], ["image", "overlay"]),
         element("Draw", "ImageOverlay", ["image", "overlay"], ["image"])],
        name="p_face1"), runtime=runtime)
    image = np.zeros((50, 100, 3), np.uint8)
    swag, okay, diagnostic = run_frame(runtime, pipeline, {"image": image})
    assert okay, diagnostic
    rect = swag["Face.overlay"]["rectangles"][0]
    assert rect == {"x": 0.1, "y": 0.1, "w": 0.2, "h": 0.6,
                    "name": "face"}
    assert pipeline.share["Face"]["detections"] == 1
    # the overlay element consumed the rectangles and drew onto the image
    assert np.asarray(swag["image"]).any()
