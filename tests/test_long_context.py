"""Context-parallel Llama forward == dense prefill logits."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiko_services_tpu.models import llama
from aiko_services_tpu.models.long_context import make_long_context_forward
from aiko_services_tpu.parallel import MeshPlan, make_mesh


@pytest.fixture(scope="module")
def setup():
    config = llama.LlamaConfig.tiny(vocab_size=128, max_seq=64)
    params = llama.init_params(jax.random.PRNGKey(0), config)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                                config.vocab_size)
    cache = llama.init_cache(config, 2, 32)
    dense_logits, _ = llama.prefill(
        params, config, tokens, cache,
        jnp.zeros((2,), dtype=jnp.int32))
    return config, params, tokens, np.asarray(dense_logits,
                                              dtype=np.float32)


@pytest.mark.parametrize("attention", ["ring", "ulysses"])
def test_cp_forward_matches_dense(setup, attention):
    config, params, tokens, dense = setup
    plan = MeshPlan(make_mesh({"sp": 4}, jax.devices()[:4]))
    forward = make_long_context_forward(config, plan, attention)
    logits = forward(params, tokens)
    np.testing.assert_allclose(np.asarray(logits, dtype=np.float32),
                               dense, atol=0.15, rtol=0.05)


def test_cp_forward_mixed_mesh(setup):
    """sp composed with dp and tp on one mesh."""
    config, params, tokens, dense = setup
    plan = MeshPlan(make_mesh({"dp": 2, "sp": 2, "tp": 2}))
    forward = make_long_context_forward(config, plan, "ring")
    logits = forward(params, tokens)
    np.testing.assert_allclose(np.asarray(logits, dtype=np.float32),
                               dense, atol=0.15, rtol=0.05)


def test_cp_requires_sp_axis(setup):
    config, *_ = setup
    plan = MeshPlan(make_mesh({"dp": 8}))
    with pytest.raises(ValueError):
        make_long_context_forward(config, plan)
