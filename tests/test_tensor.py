"""TPU data-plane substrate: bucketing, jit caches, stage placement,
tensor frames flowing through a real pipeline."""

import queue

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import run_until
from aiko_services_tpu.pipeline import Pipeline
from aiko_services_tpu.pipeline.tensor import (
    JitCache, ShapeBucketer, StagePlacement, decode_array, encode_array,
    tree_device_put)
from aiko_services_tpu.parallel import MeshPlan, P, make_mesh

ELEMENTS = "tests/pipeline_elements.py"


def element(name, cls, inputs, outputs, parameters=None):
    return {"name": name,
            "input": [{"name": n} for n in inputs],
            "output": [{"name": n} for n in outputs],
            "deploy": {"local": {"module": ELEMENTS, "class_name": cls}},
            "parameters": parameters or {}}


def definition(graph, elements, name="p_tensor"):
    return {"version": 0, "name": name, "runtime": "jax", "graph": graph,
            "parameters": {}, "elements": elements}


# -- ShapeBucketer ----------------------------------------------------------

def test_bucketer_powers_of_two():
    b = ShapeBucketer(minimum=16)
    assert b.bucket(1) == 16
    assert b.bucket(16) == 16
    assert b.bucket(17) == 32
    assert b.bucket(1000) == 1024


def test_bucketer_explicit_buckets():
    b = ShapeBucketer(buckets=[8, 64, 512])
    assert b.bucket(5) == 8
    assert b.bucket(64) == 64
    assert b.bucket(65) == 512
    with pytest.raises(ValueError):
        b.bucket(513)


def test_bucketer_pad():
    b = ShapeBucketer(buckets=[8])
    x = jnp.arange(5)
    padded, true_size = b.pad(x)
    assert padded.shape == (8,)
    assert true_size == 5
    np.testing.assert_array_equal(np.asarray(padded),
                                  [0, 1, 2, 3, 4, 0, 0, 0])


# -- JitCache ---------------------------------------------------------------

def test_jit_cache_hits_and_misses():
    cache = JitCache()
    fn = cache(lambda x: x * 2)
    fn(jnp.ones((4,)))
    fn(jnp.ones((4,)))          # same signature -> hit
    fn(jnp.ones((8,)))          # new shape -> miss
    assert cache.stats == {"hits": 1, "misses": 2, "entries": 2,
                           "signatures": 2}


def test_jit_cache_bucketed_no_recompile():
    """Bucketing keeps ragged lengths on one compiled signature."""
    cache = JitCache()
    bucketer = ShapeBucketer(buckets=[8])
    fn = cache(lambda x: x.sum())
    for n in (3, 5, 7):
        padded, _ = bucketer.pad(jnp.ones((n,)))
        fn(padded)
    assert cache.stats["signatures"] == 1


# -- StagePlacement ---------------------------------------------------------

def test_stage_placement_disjoint_submeshes():
    placement = StagePlacement(jax.devices())
    plans = placement.assign({"detect": {"dp": 2},
                              "llm": {"tp": 4},
                              "post": 2})
    all_devices = []
    for plan in plans.values():
        all_devices += list(plan.mesh.devices.flat)
    assert len(all_devices) == 8
    assert len(set(all_devices)) == 8          # disjoint
    assert dict(plans["llm"].mesh.shape) == {"tp": 4}


def test_stage_placement_overflow_rejected():
    placement = StagePlacement(jax.devices())
    with pytest.raises(ValueError, match="want"):
        placement.assign({"a": 8, "b": 1})


def test_stage_transfer_reshards():
    placement = StagePlacement(jax.devices())
    placement.assign({"a": {"dp": 4}, "b": {"tp": 4}})
    x = jnp.arange(16.0).reshape(4, 4)
    on_a = placement.transfer(x, "a", P("dp", None))
    on_b = placement.transfer(on_a, "b", P(None, "tp"))
    np.testing.assert_array_equal(np.asarray(on_b), np.asarray(x))
    assert on_b.sharding.mesh.shape["tp"] == 4


def test_tree_device_put():
    plan = MeshPlan(make_mesh({"dp": 4}, jax.devices()[:4]))
    tree = {"x": jnp.ones((8, 2)), "meta": "keep-me"}
    placed = tree_device_put(tree, plan, P("dp", None))
    assert placed["meta"] == "keep-me"
    assert placed["x"].sharding.mesh.shape["dp"] == 4


# -- replicated stages (ISSUE 7) --------------------------------------------

def test_replica_carve_splits_stage_into_disjoint_submeshes():
    placement = StagePlacement(jax.devices())
    placement.assign({"detect": "auto", "llm": 2},
                     replicas={"detect": 3})
    subs = placement.replica_plans["detect"]
    assert len(subs) == 3
    owned = [d for plan in subs for d in plan.mesh.devices.flat]
    assert len(owned) == len(set(owned)) == 6   # disjoint, 8 - llm's 2
    # The whole-stage plan spans every replica's chips as one dp pool.
    assert set(placement.plans["detect"].mesh.devices.flat) == set(owned)
    assert placement.live_replicas("detect") == [0, 1, 2]
    for device in subs[1].mesh.devices.flat:
        assert placement.replica_of("detect", device) == 1


def test_replica_fixed_request_describes_one_replica():
    placement = StagePlacement(jax.devices())
    placement.assign({"detect": {"dp": 2}}, replicas={"detect": 3})
    for plan in placement.replica_plans["detect"]:
        assert dict(plan.mesh.shape) == {"dp": 2}
    assert placement.plans["detect"].mesh.devices.size == 6


def test_replica_overflow_rejected():
    placement = StagePlacement(jax.devices())
    with pytest.raises(ValueError, match="want"):
        placement.assign({"detect": {"dp": 2}}, replicas={"detect": 5})


def test_drop_replica_retires_one_submesh_without_touching_peers():
    placement = StagePlacement(jax.devices())
    placement.assign({"detect": "auto"}, replicas={"detect": 4})
    before = [set(plan.mesh.devices.flat)
              for plan in placement.replica_plans["detect"]]
    placement.stage_sharding("detect", replica=0)
    epoch = placement.replica_epoch
    dead = placement.drop_replica("detect", 2)
    assert dead == before[2]
    # Peers keep their EXACT submeshes -- no generation bump, no
    # re-carve; only the replica epoch moves (per-replica caches).
    assert placement.generation == 0
    assert placement.replica_epoch == epoch + 1
    for index in (0, 1, 3):
        assert set(placement.replica_plans["detect"][index]
                   .mesh.devices.flat) == before[index]
    assert placement.replica_plans["detect"][2] is None
    assert placement.live_replicas("detect") == [0, 1, 3]
    # The dead chips left the pool and the stage-wide plan.
    assert not set(placement.devices) & dead
    assert not set(placement.plans["detect"].mesh.devices.flat) & dead
    # Stage shardings were invalidated (stale submesh memo).
    assert not placement._shardings
    # Dropping again is a no-op.
    assert placement.drop_replica("detect", 2) == set()


def test_reassign_restores_desired_replica_count():
    placement = StagePlacement(jax.devices())
    placement.assign({"detect": 1}, replicas={"detect": 3},
                     replica_min={"detect": 1})
    placement.drop_replica("detect", 1)
    assert len(placement.live_replicas("detect")) == 2
    generation = placement.generation
    placement.reassign()
    # 8-chip pool minus the retired chip still fits 3x1.
    assert len(placement.live_replicas("detect")) == 3
    assert placement.generation == generation + 1


def test_replace_sheds_replicas_before_halving_fixed_axes():
    placement = StagePlacement(jax.devices())
    placement.assign({"detect": {"dp": 2}, "llm": {"tp": 2}},
                     replicas={"detect": 3}, replica_min={"detect": 1})
    # Kill 4 chips: 4 survivors cannot hold 3x2 + 2, so detect sheds
    # replicas down to 1 (2 chips) before llm's tp axis halves.
    placement.replace(placement.devices[:4])
    assert len(placement.live_replicas("detect")) == 1
    assert dict(placement.plans["llm"].mesh.shape) == {"tp": 2}


def test_set_replicas_validates_and_floors():
    placement = StagePlacement(jax.devices())
    placement.assign({"detect": 1}, replicas={"detect": 2},
                     replica_min={"detect": 2})
    with pytest.raises(KeyError):
        placement.set_replicas("llm", 3)
    placement.set_replicas("detect", 1)     # floored at replica_min
    placement.reassign()
    assert len(placement.live_replicas("detect")) == 2
    placement.set_replicas("detect", 4)
    placement.reassign()
    assert len(placement.live_replicas("detect")) == 4


def test_replica_transfer_lands_on_one_submesh():
    placement = StagePlacement(jax.devices())
    placement.assign({"detect": 2}, replicas={"detect": 2})
    x = jnp.arange(16.0).reshape(4, 4)
    on_one = placement.transfer(x, "detect", replica=1)
    assert set(on_one.sharding.device_set) \
        == placement.replica_devices("detect", 1)
    np.testing.assert_array_equal(np.asarray(on_one), np.asarray(x))


# -- host codec -------------------------------------------------------------

def test_array_codec_roundtrip():
    x = np.random.default_rng(0).standard_normal((3, 5)).astype("float32")
    decoded = decode_array(encode_array(jnp.asarray(x)))
    np.testing.assert_array_equal(decoded, x)
    assert decoded.dtype == x.dtype


# -- definition-driven stage placement --------------------------------------

def test_definition_placement_two_stage_pipeline(runtime):
    """A definition file expresses a two-stage sharded pipeline: each
    element's ``placement`` block lands it on a disjoint submesh, and
    frames hop stages via StagePlacement.transfer (ICI reshard)."""
    scale_def = element("Scale", "TensorScale", ["x"], ["x"],
                        {"factor": 3.0})
    scale_def["placement"] = {"mesh": {"dp": 4}}
    sum_def = element("Sum", "TensorSum", ["x"], ["total"])
    sum_def["placement"] = {"mesh": {"tp": 4}}
    pipeline = Pipeline(definition(["(Scale Sum)"],
                                   [scale_def, sum_def]),
                        runtime=runtime)

    placement = pipeline.stage_placement
    assert placement is not None
    assert dict(placement.plan("Scale").mesh.shape) == {"dp": 4}
    assert dict(placement.plan("Sum").mesh.shape) == {"tp": 4}
    scale_devices = set(placement.plan("Scale").mesh.devices.flat)
    sum_devices = set(placement.plan("Sum").mesh.devices.flat)
    assert not scale_devices & sum_devices        # disjoint submeshes

    responses = queue.Queue()
    pipeline.process_frame_local({"x": jnp.ones((4, 4))},
                                 queue_response=responses)
    run_until(runtime, lambda: not responses.empty())
    _, _, swag, _, okay, diagnostic = responses.get()
    assert okay, diagnostic
    assert float(swag["total"]) == 48.0
    # Each element resolved ITS stage's mesh, not the local default.
    assert dict(pipeline.graph.get_node("Scale").element.plan.mesh.shape) \
        == {"dp": 4}
    assert dict(pipeline.graph.get_node("Sum").element.plan.mesh.shape) \
        == {"tp": 4}


def test_definition_placement_overflow_rejected(runtime):
    """Placement blocks requesting more chips than exist fail at
    construction, not at frame time."""
    scale_def = element("Scale", "TensorScale", ["x"], ["x"])
    scale_def["placement"] = {"devices": 8}
    sum_def = element("Sum", "TensorSum", ["x"], ["total"])
    sum_def["placement"] = {"devices": 4}
    with pytest.raises(ValueError, match="want"):
        Pipeline(definition(["(Scale Sum)"], [scale_def, sum_def]),
                 runtime=runtime)


# -- tensor frames through a real pipeline ----------------------------------

def test_tensor_pipeline_end_to_end(runtime):
    """jax.Arrays flow through TPU elements; jit cache reused across
    frames."""
    pipeline = Pipeline(definition(
        ["(Scale Sum)"],
        [element("Scale", "TensorScale", ["x"], ["x"],
                 {"factor": 3.0}),
         element("Sum", "TensorSum", ["x"], ["total"])]),
        runtime=runtime)

    def run_frame(value):
        responses = queue.Queue()
        pipeline.process_frame_local({"x": value},
                                     queue_response=responses)
        run_until(runtime, lambda: not responses.empty())
        *_, swag, metrics, okay, diagnostic = \
            (lambda t: (t[0], t[1], t[2], t[3], t[4], t[5]))(
                responses.get())
        assert okay, diagnostic
        return swag

    swag = run_frame(jnp.ones((4, 4)))
    assert float(swag["total"]) == 48.0
    swag = run_frame(jnp.ones((4, 4)) * 2)
    assert float(swag["total"]) == 96.0

    scale = pipeline.graph.get_node("Scale").element
    assert scale.jit_cache.stats["hits"] >= 1
    assert scale.jit_cache.stats["signatures"] == 1
