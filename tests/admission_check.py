"""Standalone batched-vs-single admission equality check (run by
test_models.py::test_batched_admission_matches_single in a SUBPROCESS --
see that test's docstring for why).  Exits 0 on success, 1 with a
diagnostic on mismatch.

Determinism (round 5): the exact-stream comparison requires the
[N*S, dim] batched prefill GEMM and the [S, dim] single-slot GEMM to
round IDENTICALLY.  With multi-threaded Eigen GEMMs the partitioning --
and therefore the summation order -- varies with machine load, which
flips near-tie argmaxes intermittently (~1-in-7 under a loaded host;
reproduced round 5 in fresh processes, so this, not cross-test buffer
state, was the flake's root cause).  Single-threaded GEMMs + highest
matmul precision make both shapes round identically run-to-run
(0 failures across repeated loaded-host trials)."""

import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_cpu_multi_thread_eigen=false").strip()
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np

from aiko_services_tpu.models import llama
from aiko_services_tpu.models.batching import ContinuousBatcher, Request


def main() -> int:
    config = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), config)
    prompts = [[1, 2, 3], list(range(1, 41)), list(range(5, 22)), [7]]

    def run(block, inflight):
        streams = {}
        batcher = ContinuousBatcher(params, config, max_slots=4,
                                    max_seq=64, prefill_chunk=16,
                                    decode_block=block,
                                    inflight=inflight)
        for i, prompt in enumerate(prompts):
            batcher.submit(Request(
                f"r{i}", list(prompt), max_new_tokens=6,
                emit=lambda r, t, f: streams.setdefault(r, []).append(t)))
        steps = batcher.run_until_drained(max_steps=400)
        assert steps < 400, f"did not drain in {steps} steps"
        return batcher, streams

    single, single_streams = run(1, 1)
    batched, batched_streams = run(4, 3)
    if single_streams != batched_streams:
        print(f"token stream mismatch: single={single_streams} "
              f"batched={batched_streams}")
        return 1
    if any(len(s) != 6 for s in single_streams.values()):
        print(f"budget mismatch: {single_streams}")
        return 1
    # And the caches agree over the prompt plus every decode position
    # BOTH paths define: tokens t1..t5 write positions P..P+4; the
    # final token t6's KV at P+5 is written only by the blocked path's
    # overshoot (the single path frees the slot at budget before
    # processing t6) -- a don't-care position beyond the freed slot's
    # live region, excluded here.
    single_k = np.asarray(llama.cache_array(single.cache), np.float32)
    batched_k = np.asarray(llama.cache_array(batched.cache), np.float32)
    for i, prompt in enumerate(prompts):
        extent = len(prompt) + 5
        a = batched_k[:, i, :extent]
        b = single_k[:, i, :extent]
        if not np.allclose(a, b, atol=2e-2, rtol=2e-2):
            print(f"slot {i} KV mismatch: max diff "
                  f"{np.abs(a - b).max()}")
            return 1
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
