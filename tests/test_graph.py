"""Graph parse/traverse tests (behavior parity with reference
src/aiko_services/main/utilities/graph.py and the pipeline-graph
name-mapping matrix in tests/unit/test_pipeline_graph.py)."""

import pytest

from aiko_services_tpu.utils import Graph, GraphError


def test_linear():
    graph = Graph.traverse(["(a b c)"])
    assert [n.name for n in graph.get_path()] == ["a", "b", "c"]


def test_diamond():
    graph = Graph.traverse(["(a (b d) (c d))"])
    path = [n.name for n in graph.get_path()]
    # Topological: the fan-in node d runs only after BOTH producers
    # (the reference's DFS preorder would run d before c).
    assert path == ["a", "b", "c", "d"]
    assert {s.name for s in graph.get_node("a").successors} == {"b", "c"}
    assert [s.name for s in graph.get_node("b").successors] == ["d"]
    assert [s.name for s in graph.get_node("c").successors] == ["d"]


def test_single_node():
    graph = Graph.traverse(["(a)"])
    assert [n.name for n in graph.get_path()] == ["a"]


def test_iterate_after():
    graph = Graph.traverse(["(a b c d)"])
    assert [n.name for n in graph.iterate_after("b")] == ["c", "d"]
    assert [n.name for n in graph.iterate_after("d")] == []
    with pytest.raises(GraphError):
        graph.iterate_after("zz")


def test_multiple_heads():
    graph = Graph.traverse(["(a b)", "(x y)"])
    assert [h.name for h in graph.heads] == ["a", "x"]
    assert [n.name for n in graph.get_path("x")] == ["x", "y"]


def test_predecessors():
    graph = Graph.traverse(["(a (b d) (c d))"])
    assert {n.name for n in graph.predecessors("d")} == {"b", "c"}


def test_acyclic_validation():
    graph = Graph.traverse(["(a b)"])
    graph.get_node("b").add_successor(graph.get_node("a"))
    with pytest.raises(GraphError):
        graph.validate_acyclic()
