"""Reads a pipeline parameter the registry does not know about."""


class Knobs:
    def read(self):
        return self._pipeline_parameters.get("mystery_knob")
