"""Span-bearing pipeline hooks the profiler consumes (fixture twin)."""

SPAN_HOOKS = (
    "pipeline.process_element:0", "pipeline.process_element_post:0",
    "pipeline.process_segment:0", "pipeline.process_segment_post:0",
    "pipeline.process_stage:0", "pipeline.process_stage_post:0",
    "pipeline.stage_hop:0")
