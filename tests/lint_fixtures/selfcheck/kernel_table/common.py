"""Healthy baseline: one registered hook with a matching run site."""


class Engine:
    def __init__(self):
        self.add_hook("engine.frame:0")

    def step(self):
        self.run_hook("engine.frame:0", {})
