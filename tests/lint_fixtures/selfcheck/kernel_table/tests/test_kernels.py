"""Fixture-twin equivalence test the kernels.py registry references."""


def test_undocumented_kernel():
    pass
