"""A registered, tested Pallas kernel the README table fails to list."""

KERNEL_EQUIVALENCE_TESTS = {
    "undocumented_kernel": "test_kernels.py::test_undocumented_kernel",
}


def undocumented_kernel(pl, x):
    return pl.pallas_call(lambda x_ref, o_ref: None, out_shape=x)(x)
