"""Registers a hook nothing ever runs."""


class DeadHook:
    def __init__(self):
        self.add_hook("engine.dead:0")
