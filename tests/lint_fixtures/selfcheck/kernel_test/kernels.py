"""A Pallas kernel entry point with NO registered equivalence test."""


def untested_kernel(pl, x):
    return pl.pallas_call(lambda x_ref, o_ref: None, out_shape=x)(x)
