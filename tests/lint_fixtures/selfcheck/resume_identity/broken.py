"""Resume post that carries the Frame but not its replay_epoch."""


class Parker:
    def park(self, frame):
        self.post_self("resume_element", [frame])
