"""Emits a metric series no README metrics table documents."""


class Knobs:
    def tick(self, registry):
        registry.count("mystery_metric_total")
