"""Attaches a handler to a hook nothing runs."""


class GhostHandler:
    def attach(self, handler):
        self.add_hook_handler("engine.ghost:0", handler)
