"""Deliberately broken element classes for the aiko_lint fixture
corpus (tests/test_static_analysis.py).

Each class triggers exactly ONE residency rule when referenced from its
fixture definition; the ``Clean*`` classes exist so the fixture graphs
have violation-free neighbors.  None of this is ever executed -- the
analyzers AST-parse it without importing (jax never loads).
"""

import numpy as np

from aiko_services_tpu.elements.image import as_uint8
from aiko_services_tpu.pipeline import PipelineElement
from aiko_services_tpu.pipeline.tensor import TPUElement


def _as_uint8(value):
    """Module-local wrapper around a host-materializing call: the
    analyzer must trace through it."""
    return np.asarray(value)


def _via_import(value):
    """Local wrapper around an IMPORTED host-materializing helper: the
    forcing set must seed imports before its local fixpoint."""
    return as_uint8(value)


class UndeclaredHostInput(PipelineElement):
    """np.asarray on a device input with no host_inputs declaration:
    an implicit device->host sync the swag contract counts."""

    def process_frame(self, stream, image=None):
        pixels = np.asarray(image)          # undeclared-host-input
        return True, {"n": int(pixels.size)}


class DeviceFnHostCall(TPUElement):
    """DeviceFn whose trace body host-materializes: the fused trace
    would sync (or fail) under jax.jit."""

    def device_fn(self, stream):
        from aiko_services_tpu.pipeline import DeviceFn

        def trace(image):
            scale = np.asarray(image).mean()    # device-fn-host-call
            return {"image": image * scale}

        return DeviceFn(fn=trace, inputs=("image",), outputs=("image",))

    def process_frame(self, stream, image=None):
        return True, {"image": image}


class NoParameters(PipelineElement):
    """Reads no parameters at all -- the unread-parameter fixture
    declares one on this element."""

    def process_frame(self, stream, x=None):
        return True, {"y": x}


class DeviceProducer(TPUElement):
    """Device-resident producer for the donation-alias fixture."""

    device_resident = True

    def process_frame(self, stream, x=None):
        return True, {"out": x}


class WrappedHostInput(PipelineElement):
    """Same sync as UndeclaredHostInput, but hidden behind the
    module-local ``_as_uint8`` helper."""

    def process_frame(self, stream, image=None):
        data = _as_uint8(image)             # undeclared-host-input
        return True, {"n": int(data.size)}


class ImportWrappedHostInput(PipelineElement):
    """Same sync again, through a local wrapper around an imported
    helper (``as_uint8`` lives in elements/image.py)."""

    def process_frame(self, stream, image=None):
        data = _via_import(image)           # undeclared-host-input
        return True, {"n": int(data.size)}


class SuppressedHostInput(PipelineElement):
    """Same violation as UndeclaredHostInput, but the comment escape
    hatch suppresses it -- must NOT be flagged."""

    def process_frame(self, stream, image=None):
        data = np.asarray(image)    # aiko-lint: disable=undeclared-host-input
        return True, {"n": int(data.size)}


class CleanHead(PipelineElement):
    """Violation-free head: passes frame data through."""

    def process_frame(self, stream, image=None):
        return True, {"image": image}


class CleanSink(PipelineElement):
    """Violation-free terminal sink (host-typed input declared)."""

    host_inputs = ("n", "v", "out", "image", "y")

    def process_frame(self, stream, **inputs):
        return True, {}
