# Deliberately unparseable element source: the most broken element
# possible must NOT lint clean (rule: bad-source).
class Broken(
