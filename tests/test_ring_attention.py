"""Context parallelism: ring / Ulysses / blockwise attention must match
dense causal attention exactly (same math, different schedule/placement).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiko_services_tpu.ops import attention_prefill
from aiko_services_tpu.parallel import make_mesh
from aiko_services_tpu.parallel.ring import (blockwise_attention,
                                             ring_attention,
                                             ulysses_attention)

B, S, H, D = 2, 32, 4, 16


@pytest.fixture(scope="module")
def qkv():
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, D), dtype=jnp.float32)
    k = jax.random.normal(kk, (B, S, H, D), dtype=jnp.float32)
    v = jax.random.normal(kv, (B, S, H, D), dtype=jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
    dense = attention_prefill(q, k, v, positions)
    return q, k, v, positions, dense


def test_blockwise_matches_dense(qkv):
    q, k, v, positions, dense = qkv
    out = blockwise_attention(q, k, v, positions, block_size=8)
    np.testing.assert_allclose(out, dense, atol=1e-5)


def test_blockwise_ragged_tail(qkv):
    """T not divisible by block_size exercises the pad/mask path."""
    q, k, v, positions, dense = qkv
    out = blockwise_attention(q, k, v, positions, block_size=7)
    np.testing.assert_allclose(out, dense, atol=1e-5)


def test_blockwise_offset_positions():
    """Chunked-prefill shape: queries begin mid-cache (start offset)."""
    key = jax.random.PRNGKey(1)
    t = 24
    q = jax.random.normal(key, (1, 8, H, D))
    k = jax.random.normal(jax.random.fold_in(key, 1), (1, t, H, D))
    v = jax.random.normal(jax.random.fold_in(key, 2), (1, t, H, D))
    q_pos = jnp.arange(16, 24)[None, :]
    kv_pos = jnp.arange(t)[None, :]
    dense = attention_prefill(q, k, v, q_pos)
    out = blockwise_attention(q, k, v, q_pos, kv_pos, block_size=5)
    np.testing.assert_allclose(out, dense, atol=1e-5)


def test_ring_matches_dense(qkv):
    q, k, v, positions, dense = qkv
    mesh = make_mesh({"sp": 4}, jax.devices()[:4])
    out = ring_attention(q, k, v, positions, mesh)
    np.testing.assert_allclose(out, dense, atol=1e-5)


def test_ring_full_axis(qkv):
    q, k, v, positions, dense = qkv
    mesh = make_mesh({"sp": 8})
    out = ring_attention(q, k, v, positions, mesh)
    np.testing.assert_allclose(out, dense, atol=1e-5)


def test_ring_jits(qkv):
    q, k, v, positions, dense = qkv
    mesh = make_mesh({"sp": 4}, jax.devices()[:4])
    jitted = jax.jit(lambda *a: ring_attention(*a, mesh=mesh))
    out = jitted(q, k, v, positions)
    np.testing.assert_allclose(out, dense, atol=1e-5)


def test_ulysses_matches_dense(qkv):
    q, k, v, positions, dense = qkv
    mesh = make_mesh({"sp": 4}, jax.devices()[:4])
    out = ulysses_attention(q, k, v, positions, mesh)
    np.testing.assert_allclose(out, dense, atol=1e-5)


def test_ulysses_rejects_indivisible_heads(qkv):
    q, k, v, positions, _ = qkv
    mesh = make_mesh({"sp": 8})
    with pytest.raises(ValueError):
        ulysses_attention(q, k, v, positions, mesh)


def test_ring_bfloat16(qkv):
    q, k, v, positions, _ = qkv
    q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
    dense = attention_prefill(q, k, v, positions)
    mesh = make_mesh({"sp": 4}, jax.devices()[:4])
    out = ring_attention(q, k, v, positions, mesh)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(out.astype(np.float32),
                               dense.astype(np.float32), atol=6e-2)
