"""Detector model + element (BASELINE config 2 on the CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np

from conftest import run_until
from aiko_services_tpu.models import detector
from aiko_services_tpu.pipeline import Pipeline


def test_forward_shapes():
    config = detector.DetectorConfig.tiny()
    params = detector.init_params(jax.random.PRNGKey(0), config)
    images = jnp.zeros((2, 64, 64, 3), dtype=jnp.float32)
    predictions = detector.forward(params, config, images)
    assert [tuple(p.shape) for p in predictions] == [
        (2, 8, 8, 4 + config.num_classes),
        (2, 4, 4, 4 + config.num_classes),
        (2, 2, 2, 4 + config.num_classes)]


def test_decode_boxes_in_bounds():
    config = detector.DetectorConfig.tiny()
    params = detector.init_params(jax.random.PRNGKey(0), config)
    images = jax.random.uniform(jax.random.PRNGKey(1), (1, 64, 64, 3))
    boxes, scores = detector.decode(
        config, detector.forward(params, config, images), (64, 64))
    assert boxes.shape == (1, 8 * 8 + 4 * 4 + 2 * 2, 4)
    assert scores.shape[-1] == config.num_classes
    # centers inside the image; box widths positive
    assert bool((boxes[..., 2] >= boxes[..., 0]).all())
    assert bool((boxes[..., 3] >= boxes[..., 1]).all())


def test_nms_suppresses_overlaps():
    config = detector.DetectorConfig.tiny(num_classes=2)
    boxes = jnp.asarray([[0.1, 0.1, 0.5, 0.5],
                         [0.12, 0.12, 0.52, 0.52],     # overlaps first
                         [0.6, 0.6, 0.9, 0.9]])
    scores = jnp.asarray([[0.9, 0.0],
                          [0.8, 0.0],
                          [0.0, 0.7]])
    result = detector.nms(config, boxes, scores)
    valid = np.asarray(result["valid"])
    kept_boxes = np.asarray(result["boxes"])[valid]
    assert valid.sum() == 2
    np.testing.assert_allclose(kept_boxes[0], [0.1, 0.1, 0.5, 0.5],
                               atol=1e-6)
    np.testing.assert_allclose(kept_boxes[1], [0.6, 0.6, 0.9, 0.9],
                               atol=1e-6)
    assert np.asarray(result["classes"])[valid].tolist() == [0, 1]


def test_nms_score_threshold():
    config = detector.DetectorConfig.tiny(num_classes=1)
    boxes = jnp.asarray([[0.1, 0.1, 0.2, 0.2], [0.5, 0.5, 0.6, 0.6]])
    scores = jnp.asarray([[0.9], [0.1]])          # second below 0.25
    result = detector.nms(config, boxes, scores)
    assert np.asarray(result["valid"]).sum() == 1


def test_detect_jits_end_to_end():
    config = detector.DetectorConfig.tiny()
    params = detector.init_params(jax.random.PRNGKey(0), config)
    images = jax.random.uniform(jax.random.PRNGKey(1), (2, 64, 64, 3))
    result = detector.detect(params, config, images)
    assert result["boxes"].shape == (2, config.max_detections, 4)
    assert result["valid"].dtype == bool


def test_detector_element_pipeline(tmp_path, runtime):
    """image -> Detector -> ImageOverlay -> write, end to end."""
    from PIL import Image
    source = tmp_path / "in.png"
    Image.new("RGB", (64, 64), (128, 90, 40)).save(source)
    target = tmp_path / "out.png"

    def element(name, cls, inputs, outputs, parameters=None,
                module="aiko_services_tpu.elements"):
        return {"name": name,
                "input": [{"name": n} for n in inputs],
                "output": [{"name": n} for n in outputs],
                "deploy": {"local": {"module": module,
                                     "class_name": cls}},
                "parameters": parameters or {}}

    pipeline = Pipeline({
        "version": 0, "name": "p_detect", "runtime": "jax",
        "graph": ["(Read Detect Overlay Write)"],
        "parameters": {},
        "elements": [
            element("Read", "ImageReadFile", ["path"], ["image"],
                    {"data_sources": f"file://{source}"}),
            element("Detect", "Detector", ["image"],
                    ["image", "overlay", "detections"],
                    {"score_threshold": 0.0},
                    module="aiko_services_tpu.elements.detect"),
            element("Overlay", "ImageOverlay", ["image", "overlay"],
                    ["image"]),
            element("Write", "ImageWriteFile", ["image"], ["path"],
                    {"data_targets": f"file://{target}"})]},
        runtime=runtime)
    pipeline.create_stream_local("s1", {})
    assert run_until(runtime, lambda: target.exists(), timeout=30.0)

    detect_element = pipeline.graph.get_node("Detect").element
    assert detect_element.jit_cache.stats["misses"] == 1
