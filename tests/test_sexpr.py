"""S-expression codec tests (behavior parity with reference
src/aiko_services/main/utilities/parser.py round-trip cases)."""

import pytest

from aiko_services_tpu.utils import (generate, generate_value, parse,
                                     parse_value, parse_number,
                                     SExprError)


def test_simple_command():
    command, params = parse("(add topic name)")
    assert command == "add"
    assert params == ["topic", "name"]


def test_empty_command():
    command, params = parse("(sync)")
    assert command == "sync"
    assert params == []


def test_nested_lists():
    command, params = parse("(a (b c) d)")
    assert command == "a"
    assert params == [["b", "c"], "d"]


def test_dictionary():
    command, params = parse("(process_frame (stream_id: 1) (a: 0))")
    assert command == "process_frame"
    assert params == [{"stream_id": "1"}, {"a": "0"}]


def test_nested_dictionary():
    value = parse_value("(outer: (inner: 42) other: x)")
    assert value == {"outer": {"inner": "42"}, "other": "x"}


def test_quoted_strings():
    command, params = parse('(say "hello world" plain)')
    assert params == ["hello world", "plain"]


def test_quoted_escape():
    command, params = parse(r'(say "a \"quoted\" word")')
    assert params == ['a "quoted" word']


def test_length_prefixed_token():
    # 11 raw chars including a space and parenthesis
    text = '(blob 11:ab cd(ef) g tail)'
    command, params = parse(text)
    assert params == ["ab cd(ef) g", "tail"]


def test_generate_roundtrip():
    payload = generate("add", ["topic/path", "name", 3, 2.5, True,
                               ["t1", "t2"], {"k": "v"}])
    command, params = parse(payload)
    assert command == "add"
    assert params[0] == "topic/path"
    assert params[2] == "3"
    assert params[5] == ["t1", "t2"]
    assert params[6] == {"k": "v"}


def test_generate_quoting():
    payload = generate("say", ["hello world"])
    assert parse(payload)[1] == ["hello world"]


def test_generate_special_chars_roundtrip():
    nasty = 'line1\nline"2\\x'
    payload = generate("blob", [nasty])
    assert parse(payload)[1] == [nasty]


def test_parse_number():
    assert parse_number("42") == 42
    assert parse_number("2.5") == 2.5
    assert parse_number("true") is True
    assert parse_number("false") is False
    assert parse_number("nil") is None
    assert parse_number("abc") == "abc"
    assert parse_number("abc", 7) == 7


def test_errors():
    with pytest.raises(SExprError):
        parse("(unterminated")
    with pytest.raises(SExprError):
        parse("(a) trailing")


def test_bare_atom():
    value, params = parse("atom")
    assert value == "atom"
    assert params == []
