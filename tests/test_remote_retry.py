"""Regression: a frame reaching a not-yet-discovered remote stage must
retry FROM that stage -- earlier elements must not re-execute -- and must
count as in-flight so graceful destroy does not drop it."""

import queue

from conftest import run_until

from aiko_services_tpu.pipeline import Pipeline
from aiko_services_tpu.services import Registrar


def _element(name, cls):
    return {"name": name, "input": [{"name": "x"}],
            "output": [{"name": "x"}],
            "deploy": {"local": {
                "module": "aiko_services_tpu.elements.common",
                "class_name": cls}}}


def _remote(name, target):
    return {"name": name, "input": [{"name": "x"}],
            "output": [{"name": "x"}],
            "deploy": {"remote": {"name": target}}}


def test_frame_waits_for_remote_without_reexecution(runtime):
    Registrar(runtime=runtime, primary_search_timeout=0.05)
    front = Pipeline({"version": 0, "name": "front", "runtime": "jax",
                      "graph": ["(inc fwd)"],
                      "elements": [_element("inc", "Increment"),
                                   _remote("fwd", "back")]},
                     runtime=runtime)
    responses = queue.Queue()
    front.create_stream_local("1", queue_response=responses)
    # Ingest BEFORE the backend pipeline exists: the frame must park and
    # retry, with inc having run exactly once.
    front.ingest_local("1", {"x": 0}, queue_response=responses)
    runtime.run(timeout=0.6)          # several retry cycles, no backend
    assert front.streams["1"].in_flight == 1     # parked, not dropped

    back = Pipeline({"version": 0, "name": "back", "runtime": "jax",
                     "graph": ["(inc)"],
                     "elements": [_element("inc", "Increment")]},
                    runtime=runtime)
    assert run_until(runtime, lambda: not responses.empty(), timeout=10.0)
    _, _, swag, _, okay, diagnostic = responses.get()
    assert okay, diagnostic
    # front inc once (0 -> 1), back inc once (1 -> 2): NOT 3+.
    assert int(swag["x"]) == 2, swag
    assert front.streams["1"].in_flight == 0
    front.stop()
    back.stop()
