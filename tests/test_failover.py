"""Process-level fault domain (ISSUE 13): durable stream journals,
gateway failover with journal adoption, drain/rolling-restart handoff,
LLM committed-prefix resume across processes, and gateway idle-session
reaping.

The acceptance shape: two serving pipelines + a standalone gateway on
one loopback runtime; killing a pipeline (the in-process SIGKILL twin:
``Pipeline.kill`` / the ``process_kill`` fault point) fires its
per-service LWT, the registrar reaps it, the gateway re-binds the live
WebSocket sessions to the survivor, the survivor ADOPTS the dead
pipeline's journal, and results resume in order with no duplicates.
The multi-process variant (real SIGKILL over the native MQTT broker)
is the ``slow``-marked chaos driver test at the bottom.
"""

import json
import queue
import threading
import time

import pytest

from conftest import run_until

from aiko_services_tpu.gateway.client import GatewayClient
from aiko_services_tpu.gateway.server import GatewayServer
from aiko_services_tpu.pipeline import (DefinitionError, Pipeline,
                                        decode_frame_data)
from aiko_services_tpu.pipeline.journal import (StreamJournal,
                                                claim_adoption,
                                                adopter_of,
                                                load_journal)
from aiko_services_tpu.services import Registrar
from aiko_services_tpu.utils import parse

COMMON = "aiko_services_tpu.elements.common"


def stage(name, busy_ms=1.0, factor=2.0, devices=2):
    return {"name": name, "input": [{"name": "x"}],
            "output": [{"name": "x"}],
            "parameters": {"busy_ms": busy_ms, "factor": factor},
            "placement": {"devices": devices},
            "deploy": {"local": {"module": COMMON,
                                 "class_name": "StageWork"}}}


def serving(runtime, name, journal_dir, busy_ms=1.0, extra=None):
    """Two placed stages (the scheduler activates: frames park at
    stage workers, so in-flight work is genuinely asynchronous).
    work*2 then finish*3 -> every result is x*6."""
    parameters = {"journal": "on", "journal_dir": str(journal_dir)}
    parameters.update(extra or {})
    return Pipeline({"version": 0, "name": name, "runtime": "jax",
                     "graph": ["(work finish)"],
                     "parameters": parameters,
                     "elements": [stage("work", busy_ms),
                                  stage("finish", busy_ms,
                                        factor=3.0)]},
                    runtime=runtime)


def llm_pipeline(runtime, name, journal_dir, fault_plan=None,
                 max_new=96):
    parameters = {"journal": "on", "journal_dir": str(journal_dir)}
    if fault_plan is not None:
        parameters["fault_plan"] = json.dumps(fault_plan)
    element = {"name": "llm", "input": [{"name": "text"}],
               "output": [{"name": "text"}],
               "parameters": {"max_new_tokens": max_new,
                              "temperature": 0.0, "max_seq": 256,
                              "decode_block_tokens": 4},
               "deploy": {"local": {
                   "module": "aiko_services_tpu.elements.llm",
                   "class_name": "LLM"}}}
    return Pipeline({"version": 0, "name": name, "runtime": "jax",
                     "graph": ["(llm)"], "parameters": parameters,
                     "elements": [element]}, runtime=runtime)


def in_thread(target):
    box: dict = {}

    def body():
        try:
            box["value"] = target()
        except Exception as error:      # surfaced by the test
            box["error"] = error
    thread = threading.Thread(target=body, daemon=True)
    thread.start()
    return thread, box


def finish(runtime, thread, box, timeout=90.0):
    run_until(runtime, lambda: not thread.is_alive(), timeout=timeout)
    assert not thread.is_alive(), "client interaction hung"
    if "error" in box:
        raise box["error"]
    return box.get("value")


# -- journal unit behavior --------------------------------------------------

def test_journal_roundtrip_prune_and_llm(tmp_path):
    import numpy as np
    journal = StreamJournal(tmp_path / "p.journal", fsync_ms=0.0)
    journal.stream_open("s1", {"tenant": "t1", "qos_class": "batch"},
                        topic_response="ns/x/in")
    journal.frame_ingested("s1", 0, {"x": np.ones((2,), np.float32)})
    journal.frame_ingested("s1", 1, {"x": 2.5, "note": "hi"})
    journal.llm_token("s1", 1, 42)
    journal.llm_tokens("s1", 1, [43, 44])
    journal.frame_done("s1", 0, ok=True)
    journal.stream_open("s2", {})
    journal.stream_close("s2")
    journal.close()

    state = load_journal(journal.path)
    assert not state.drained and not state.truncated
    live = {entry.stream_id: entry for entry in state.live_streams()}
    assert set(live) == {"s1"}          # s2 closed gracefully
    entry = live["s1"]
    assert entry.parameters["tenant"] == "t1"
    assert entry.topic_response == "ns/x/in"
    assert entry.delivered == [0] and entry.undelivered == [1]
    assert 0 not in entry.frames        # pruned into the watermark
    assert entry.done_upto == 0
    assert entry.llm == {1: [42, 43, 44]}
    payload = decode_frame_data({
        key: value for key, value in entry.frames[1]["data"].items()})
    assert payload["x"] == 2.5 and payload["note"] == "hi"


def test_journal_tolerates_torn_tail(tmp_path):
    journal = StreamJournal(tmp_path / "p.journal")
    journal.stream_open("s1", {})
    journal.frame_ingested("s1", 0, {"x": 1})
    journal.close()
    with open(journal.path, "a", encoding="utf-8") as stream:
        stream.write('{"t":"done","s":"s1","f":0')     # torn mid-write
    state = load_journal(journal.path)
    assert state.truncated
    # the torn done record is ignored: frame 0 is still undelivered
    assert state.streams["s1"].undelivered == [0]


def test_journal_compacts_to_live_set(tmp_path):
    journal = StreamJournal(tmp_path / "p.journal", fsync_ms=0.0,
                            compact_records=128)
    journal.stream_open("s1", {})
    for index in range(400):
        journal.frame_ingested("s1", index, {"x": index})
        journal.frame_done("s1", index)
    assert journal.compactions >= 1
    # the file holds ~the live set, not the whole history
    with open(journal.path, "r", encoding="utf-8") as stream:
        lines = stream.readlines()
    assert len(lines) < 400
    state = load_journal(journal.path)
    entry = state.streams["s1"]
    assert entry.undelivered == []
    assert len(entry.delivered) == 400      # delivered-set intact


def test_adoption_claim_is_exclusive(tmp_path):
    path = str(tmp_path / "p.journal")
    open(path, "w").close()
    assert claim_adoption(path, "peer-a") is True
    assert claim_adoption(path, "peer-b") is False
    assert adopter_of(path) == "peer-a"


def test_journal_on_without_dir_is_create_time_error(runtime):
    with pytest.raises(DefinitionError, match="journal_dir"):
        Pipeline({"version": 0, "name": "nodir", "runtime": "jax",
                  "graph": ["(work)"],
                  "parameters": {"journal": "on"},
                  "elements": [stage("work")]}, runtime=runtime)
    # the failed create must not leak a half-bound service
    assert "nodir" not in [getattr(s, "name", "") for s in
                           runtime.services()]


# -- batcher export/import resume ------------------------------------------

def test_batcher_export_import_continues_byte_identical():
    import jax
    from aiko_services_tpu.models import llama
    from aiko_services_tpu.models.batching import (ContinuousBatcher,
                                                   Request)
    config = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), config)
    prompt = [3, 5, 7, 11]
    total = 24

    def collector(sink):
        def emit(_rid, token, _finished):
            sink.append(int(token))
        return emit

    # Reference: one uninterrupted run.
    reference: list = []
    ref = ContinuousBatcher(params, config, max_slots=2)
    ref.submit(Request("r", list(prompt), max_new_tokens=total,
                       temperature=0.0, emit=collector(reference)))
    ref.run_until_drained()

    # Interrupted: export after ~8 tokens, import into a FRESH batcher
    # (a different process, as far as device state is concerned).
    first: list = []
    b1 = ContinuousBatcher(params, config, max_slots=2)
    b1.submit(Request("r", list(prompt), max_new_tokens=total,
                      temperature=0.0, emit=collector(first)))
    while len(first) < 8:
        b1.step()
    exported = b1.export_state()
    assert len(exported) == 1
    entry = exported[0]
    assert entry["prompt"] == prompt
    assert entry["committed"] == first[:len(entry["committed"])]

    second: list = []
    b2 = ContinuousBatcher(params, config, max_slots=2)
    b2.import_state(exported,
                    emit_factory=lambda _entry: collector(second))
    b2.run_until_drained()
    resumed = entry["committed"] + second
    assert resumed == reference
    assert len(resumed) == len(reference)


def test_resume_request_refuses_finished_prefix():
    """A committed prefix that already finished the request (EOS tail
    or spent budget -- the process died between the final emit and
    delivery) must NOT resume decoding: the adopter completes from
    the prefix, or the client would get a spurious post-EOS tail."""
    import jax
    from aiko_services_tpu.models import llama
    from aiko_services_tpu.models.batching import (ContinuousBatcher,
                                                   Request)
    config = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), config)
    batcher = ContinuousBatcher(params, config, max_slots=2)

    spent = Request("spent", [1, 2, 3], max_new_tokens=4,
                    eos_tokens=(99,))
    batcher.submit(spent)
    assert batcher.resume_request(spent, [5, 6, 7, 8]) is False
    assert spent.done and spent not in batcher.pending

    eos_tail = Request("eos", [1, 2, 3], max_new_tokens=16,
                       eos_tokens=(99,))
    batcher.submit(eos_tail)
    assert batcher.resume_request(eos_tail, [5, 99]) is False
    assert eos_tail.done and eos_tail not in batcher.pending

    live = Request("live", [1, 2, 3], max_new_tokens=16,
                   eos_tokens=(99,))
    batcher.submit(live)
    assert batcher.resume_request(live, [5, 6]) is True
    assert not live.done and live in batcher.pending
    assert live.generated == 2 and live.rebased == 2


# -- failover acceptance (kill -> adopt -> resume) --------------------------

def test_ws_session_fails_over_on_kill(runtime, tmp_path):
    """ISSUE 13 acceptance: SIGKILL (in-process twin) of the pipeline
    serving a live gateway session -> LWT detected -> session re-bound
    -> stream adopted from the journal -> in-order, duplicate-free
    delivery resumes on the SAME WebSocket."""
    Registrar(runtime=runtime, primary_search_timeout=0.05)
    p1 = serving(runtime, "srv1", tmp_path, busy_ms=120.0)
    gateway = GatewayServer(runtime=runtime)
    run_until(runtime, lambda: len(gateway._peers) == 1)
    p2 = serving(runtime, "srv2", tmp_path, busy_ms=5.0)
    run_until(runtime, lambda: len(gateway._peers) == 2)
    assert list(gateway._peers.values())[0] == "srv1"

    client = GatewayClient("127.0.0.1", gateway.port, timeout=90.0)
    n_frames = 6

    def phase_send():
        client.open(session="s1", tenant="t1")
        for index in range(n_frames):
            client.send_frame({"x": [float(index + 1)] * 4})
        return client.next_result()     # at least one from srv1

    thread, box = in_thread(phase_send)
    first = finish(runtime, thread, box)
    assert first["frame"] == 0 and first["ok"]

    # journal durability: every ingested frame is accounted for in
    # srv1's journal (delivered watermark + undelivered payloads)
    entry = load_journal(tmp_path / "srv1.journal").streams["gw/s1"]
    assert len(entry.delivered) + len(entry.undelivered) == n_frames

    p1.kill()                           # unclean death, mid-stream
    run_until(runtime, lambda: gateway.failovers == 1, timeout=10.0)
    run_until(runtime, lambda: p2.share["streams_adopted"] == 1,
              timeout=10.0)

    def phase_recv():
        return [client.next_result() for _ in range(n_frames - 1)]

    thread, box = in_thread(phase_recv)
    rest = finish(runtime, thread, box)
    results = [first] + rest
    # in-order, duplicate-free, every frame answered exactly once
    assert [r["frame"] for r in results] == list(range(n_frames))
    for index, result in enumerate(results):
        assert result["ok"], result
        assert result["data"]["x"][0] == pytest.approx(
            6.0 * (index + 1))
    assert p2.share["frames_journal_replayed"] >= 1
    # the adopter's ring carries the adopt event
    events = [e for e in p2.recorder.snapshot()
              if e[1] == "adopt"] if p2.recorder else []
    assert events, "adopt ring event missing"

    # post-failover: NEW frames flow to the survivor on the same session
    def phase_more():
        client.send_frame({"x": [100.0] * 4})
        result = client.next_result()
        client.close()
        return result

    thread, box = in_thread(phase_more)
    more = finish(runtime, thread, box)
    assert more["frame"] == n_frames and more["ok"]
    assert more["data"]["x"][0] == pytest.approx(600.0)
    gateway.stop()
    p2.stop()


def test_refire_covers_wire_transit_loss_on_kill(runtime, tmp_path):
    """A frame in wire transit at the kill reached NO journal --
    adoption cannot replay it.  The gateway's retransmit line
    (``_Session.unanswered``) must re-fire its own copy at the
    survivor after the re-bind; before it existed these frames were
    simply gone and the session stalled a window slot forever."""
    Registrar(runtime=runtime, primary_search_timeout=0.05)
    p1 = serving(runtime, "srv1", tmp_path, busy_ms=5.0)
    gateway = GatewayServer(runtime=runtime)
    run_until(runtime, lambda: len(gateway._peers) == 1)
    p2 = serving(runtime, "srv2", tmp_path, busy_ms=5.0)
    run_until(runtime, lambda: len(gateway._peers) == 2)
    assert list(gateway._peers.values())[0] == "srv1"

    client = GatewayClient("127.0.0.1", gateway.port, timeout=90.0)

    def phase_send():
        client.open(session="rf", tenant="t1")
        for index in range(2):
            client.send_frame({"x": [float(index + 1)] * 2})
        return [client.next_result(), client.next_result()]

    thread, box = in_thread(phase_send)
    first = finish(runtime, thread, box)
    assert [r["frame"] for r in first] == [0, 1]

    p1.kill()               # handlers gone; failover not yet begun

    def phase_transit():
        # Dispatched at srv1's now-dead topic: dropped on the floor,
        # past every journal's horizon.
        client.send_frame({"x": [3.0] * 2})
        client.send_frame({"x": [4.0] * 2})

    thread, box = in_thread(phase_transit)
    finish(runtime, thread, box)
    # the dead pipeline never saw them: its crash-time journal holds
    # only the two frames it delivered
    entry = load_journal(tmp_path / "srv1.journal").streams["gw/rf"]
    assert 2 not in entry.frames and 3 not in entry.frames

    run_until(runtime, lambda: gateway.failovers == 1, timeout=10.0)

    def phase_recv():
        results = [client.next_result(timeout=60.0) for _ in range(2)]
        client.close()
        return results

    thread, box = in_thread(phase_recv)
    rest = finish(runtime, thread, box)
    assert [r["frame"] for r in rest] == [2, 3]
    for result, x in zip(rest, (3.0, 4.0)):
        assert result["ok"], result
        assert result["data"]["x"][0] == pytest.approx(6.0 * x)
    gateway.stop()
    p2.stop()


def test_process_kill_fault_point_drives_failover(runtime, tmp_path):
    """The armed ``process_kill`` fault point IS the kill switch: the
    pipeline dies on the rule-matched ingest, deterministically."""
    Registrar(runtime=runtime, primary_search_timeout=0.05)
    plan = [{"point": "process_kill", "target": "srv1", "after": 2}]
    p1 = serving(runtime, "srv1", tmp_path,
                 extra={"fault_plan": json.dumps(plan)})
    gateway = GatewayServer(runtime=runtime)
    run_until(runtime, lambda: len(gateway._peers) == 1)
    p2 = serving(runtime, "srv2", tmp_path)
    run_until(runtime, lambda: len(gateway._peers) == 2)

    client = GatewayClient("127.0.0.1", gateway.port, timeout=90.0)
    n_frames = 5

    def interact():
        client.open(session="sk", tenant="t1")
        results = []
        for index in range(n_frames):
            # One at a time: frames sent AFTER the kill but BEFORE
            # the failover would be lost in flight to a dead process
            # (beyond the journal horizon, by design) -- lock-step
            # keeps exactly one frame exposed, and that one is
            # journaled at ingest before the kill fires.
            client.send_frame({"x": [float(index + 1)] * 2})
            results.append(client.next_result(timeout=60.0))
        client.close()
        return results

    thread, box = in_thread(interact)
    results = finish(runtime, thread, box)
    assert [r["frame"] for r in results] == list(range(n_frames))
    assert all(r["ok"] for r in results)
    # the rule fired exactly once: frame 2's ingest killed srv1 (its
    # journaled frame replayed on srv2); 2 frames ran on srv1
    assert p1._faults.fired("process_kill") == 1
    assert gateway.failovers == 1
    assert p2.share["frames_journal_replayed"] >= 1
    gateway.stop()
    p2.stop()


def test_failover_waits_for_a_survivor_to_appear(runtime, tmp_path):
    """A death with NO surviving peer must not strand the sessions
    forever: the failover parks pending and replays when the next
    peer registers."""
    Registrar(runtime=runtime, primary_search_timeout=0.05)
    p1 = serving(runtime, "solo", tmp_path, busy_ms=60.0)
    gateway = GatewayServer(runtime=runtime)
    run_until(runtime, lambda: len(gateway._peers) == 1)

    client = GatewayClient("127.0.0.1", gateway.port, timeout=90.0)
    n_frames = 3

    def phase_send():
        client.open(session="w1", tenant="t1")
        for index in range(n_frames):
            client.send_frame({"x": [float(index + 1)] * 2})

    thread, box = in_thread(phase_send)
    finish(runtime, thread, box)
    run_until(runtime, lambda: len(load_journal(
        tmp_path / "solo.journal").streams.get(
        "gw/w1", type("E", (), {"frames": {}})).frames) == n_frames,
        timeout=10.0)
    p1.kill()                           # ... and no peer exists
    runtime.run(timeout=0.4)
    assert gateway.failovers == 0       # nothing to fail over TO
    assert gateway._pending_failovers   # parked, not forgotten

    late = serving(runtime, "late", tmp_path, busy_ms=5.0)
    run_until(runtime, lambda: gateway.failovers == 1, timeout=10.0)

    def phase_recv():
        results = [client.next_result(timeout=60.0)
                   for _ in range(n_frames)]
        client.close()
        return results

    thread, box = in_thread(phase_recv)
    results = finish(runtime, thread, box)
    assert [r["frame"] for r in results] == list(range(n_frames))
    assert all(r["ok"] for r in results)
    assert late.share["streams_adopted"] == 1
    gateway.stop()
    late.stop()


def test_kill_during_llm_generation_resumes_committed_prefix(
        runtime, tmp_path):
    """Kill mid-generation: the survivor resumes at the journaled
    committed prefix and the final text is BYTE-IDENTICAL to an
    uninterrupted run at temperature 0 -- nothing re-emitted, nothing
    lost."""
    prompt = "tell me about tpus"
    # Reference text from an uninterrupted pipeline, stopped before
    # the gateway exists so it never joins the peer pool.
    ref = llm_pipeline(runtime, "ref", tmp_path / "ref")
    responses = queue.Queue()
    ref.create_stream_local("r", queue_response=responses)
    ref.process_frame_local({"text": prompt}, stream_id="r")
    assert run_until(runtime, lambda: not responses.empty(),
                     timeout=120.0)
    (_, _, swag, _, okay, diagnostic) = responses.get()
    assert okay, diagnostic
    expected = swag["text"]
    assert expected
    ref.stop()
    # forget the reference service entirely: it must not register as
    # a pipeline peer when the registrar promotes below
    runtime.remove_service(ref.service_id)

    Registrar(runtime=runtime, primary_search_timeout=0.05)
    # Pace generation (30 ms per 4-token block) so the kill lands
    # mid-generation deterministically.
    pace = [{"point": "decode_block", "target": "llm",
             "delay_ms": 30, "count": "forever"}]
    p1 = llm_pipeline(runtime, "llm1", tmp_path, fault_plan=pace)
    gateway = GatewayServer(runtime=runtime)
    run_until(runtime, lambda: len(gateway._peers) == 1)
    p2 = llm_pipeline(runtime, "llm2", tmp_path)
    run_until(runtime, lambda: len(gateway._peers) == 2)

    client = GatewayClient("127.0.0.1", gateway.port, timeout=180.0)

    def phase_send():
        client.open(session="gen", tenant="t1")
        client.send_frame({"text": prompt})

    thread, box = in_thread(phase_send)
    finish(runtime, thread, box)

    journal_path = tmp_path / "llm1.journal"

    def tokens_committed():
        state = load_journal(journal_path)
        entry = state.streams.get("gw/gen")
        return sum(len(tokens) for tokens in entry.llm.values()) \
            if entry else 0

    run_until(runtime, lambda: tokens_committed() >= 4, timeout=120.0)
    committed_at_kill = tokens_committed()
    p1.kill()
    run_until(runtime, lambda: gateway.failovers == 1, timeout=10.0)

    def phase_recv():
        result = client.next_result(timeout=180.0)
        client.close()
        return result

    thread, box = in_thread(phase_recv)
    result = finish(runtime, thread, box, timeout=180.0)
    assert result["ok"], result
    assert result["data"]["text"] == expected     # byte-identical
    if committed_at_kill < len(expected):
        # the interesting case actually happened: generation was cut
        # mid-flight and the survivor continued it
        assert p2.share["streams_adopted"] == 1
    gateway.stop()
    p2.stop()


# -- drain / rolling restart ------------------------------------------------

def test_drain_hands_off_with_zero_drop(runtime, tmp_path):
    """Cooperative drain under load: in-flight frames finish or park,
    held frames journal, the survivor adopts -- the client sees every
    frame exactly once, in order (the rolling-restart contract)."""
    Registrar(runtime=runtime, primary_search_timeout=0.05)
    p1 = serving(runtime, "srv1", tmp_path, busy_ms=80.0,
                 extra={"drain_timeout_ms": 400})
    gateway = GatewayServer(runtime=runtime)
    run_until(runtime, lambda: len(gateway._peers) == 1)
    p2 = serving(runtime, "srv2", tmp_path, busy_ms=5.0)
    run_until(runtime, lambda: len(gateway._peers) == 2)

    client = GatewayClient("127.0.0.1", gateway.port, timeout=90.0)
    n_frames = 6

    def phase_send():
        client.open(session="d1", tenant="t1")
        for index in range(n_frames):
            client.send_frame({"x": [float(index + 1)] * 2})
        return client.next_result()

    thread, box = in_thread(phase_send)
    first = finish(runtime, thread, box)
    assert first["ok"]

    p1.drain()                          # mid-stream, frames in flight
    run_until(runtime, lambda: p1.share.get("drained"), timeout=10.0)
    run_until(runtime, lambda: gateway.failovers == 1, timeout=10.0)

    def phase_recv():
        results = [client.next_result() for _ in range(n_frames - 1)]
        client.close()
        return results

    thread, box = in_thread(phase_recv)
    rest = finish(runtime, thread, box)
    results = [first] + rest
    assert [r["frame"] for r in results] == list(range(n_frames))
    assert all(r["ok"] for r in results)
    # clean drain: journal carries the drained marker
    assert load_journal(tmp_path / "srv1.journal").drained
    gateway.stop()
    p2.stop()


def test_kill_during_drain_completes_on_survivor(runtime, tmp_path):
    """A drain that never finishes (process dies mid-drain) degrades
    to the unclean path: everything journaled so far -- including
    frames held by the drain -- is adopted and completed by the
    survivor."""
    Registrar(runtime=runtime, primary_search_timeout=0.05)
    p1 = serving(runtime, "srv1", tmp_path, busy_ms=150.0,
                 extra={"drain_timeout_ms": 60000})
    gateway = GatewayServer(runtime=runtime)
    run_until(runtime, lambda: len(gateway._peers) == 1)
    p2 = serving(runtime, "srv2", tmp_path, busy_ms=5.0)
    run_until(runtime, lambda: len(gateway._peers) == 2)

    client = GatewayClient("127.0.0.1", gateway.port, timeout=90.0)
    n_frames = 4

    def phase_send():
        client.open(session="dk", tenant="t1")
        for index in range(n_frames):
            client.send_frame({"x": [float(index + 1)] * 2})

    thread, box = in_thread(phase_send)
    finish(runtime, thread, box)
    run_until(runtime, lambda: len(load_journal(
        tmp_path / "srv1.journal").streams.get(
        "gw/dk", type("E", (), {"frames": {}})).frames) == n_frames,
        timeout=10.0)

    p1.drain()
    runtime.run(timeout=0.1)            # drain starts, nowhere near done
    assert not p1.share.get("drained")
    p1.kill()                           # die mid-drain
    run_until(runtime, lambda: gateway.failovers == 1, timeout=10.0)

    def phase_recv():
        results = [client.next_result() for _ in range(n_frames)]
        client.close()
        return results

    thread, box = in_thread(phase_recv)
    results = finish(runtime, thread, box)
    assert [r["frame"] for r in results] == list(range(n_frames))
    assert all(r["ok"] for r in results)
    gateway.stop()
    p2.stop()


# -- adoption refusal -------------------------------------------------------

def test_double_adoption_refused(runtime, tmp_path):
    """One journal, one adopter: the claim file fences the second
    claimant, and a stream id already live locally is refused
    individually."""
    journal = StreamJournal(tmp_path / "dead.journal", fsync_ms=0.0)
    journal.stream_open("s1", {"tenant": "t1"})
    journal.frame_ingested("s1", 0, {"x": 1.0})
    journal.frame_done("s1", 0)
    journal.frame_ingested("s1", 1, {"x": 2.0})
    journal.close()

    p2 = serving(runtime, "peer2", tmp_path)
    p3 = serving(runtime, "peer3", tmp_path)
    got = []
    topic = f"{runtime.topic_path_process}/test/adopt"
    runtime.add_message_handler(
        lambda _topic, payload: got.append(payload), topic)

    assert p2.adopt("dead", topic) == 1
    run_until(runtime, lambda: len(got) == 1, timeout=10.0)
    command, parameters = parse(got[0])
    assert command == "process_frame_response"
    header = dict(parameters[0])
    # ONLY the undelivered frame replayed -- the delivered seq is
    # dropped, not duplicated
    assert int(header["frame_id"]) == 1
    assert str(header["okay"]).lower() != "false"

    # second adopter: refused by the claim file
    assert p3.adopt("dead", topic) == 0
    # same adopter again: the claim file fences replays too
    assert p2.adopt("dead", topic) == 0
    runtime.run(timeout=0.3)
    assert len(got) == 1                # no duplicate delivery, ever
    p2.stop()
    p3.stop()


def test_unclean_shutdown_replay_no_drop_no_dup(runtime, tmp_path):
    """Journal replay after an unclean shutdown: every undelivered
    frame replays exactly once, every delivered seq stays delivered."""
    import numpy as np
    p1 = serving(runtime, "crashy", tmp_path, busy_ms=1.0)
    responses = queue.Queue()
    p1.create_stream_local("s", queue_response=responses)
    for index in range(3):
        p1.process_frame_local(
            {"x": np.asarray([1.0 * index], np.float32)},
            stream_id="s")
    run_until(runtime, lambda: responses.qsize() == 3, timeout=30.0)
    # two more ingests that never complete: kill before processing by
    # posting the kill between them on the mailbox
    p1.process_frame_local({"x": np.asarray([100.0], np.float32)},
                           stream_id="s")
    p1.process_frame_local({"x": np.asarray([200.0], np.float32)},
                           stream_id="s")
    p1.post_self("kill")
    run_until(runtime, lambda: getattr(p1, "_killed", False),
              timeout=10.0)

    state = load_journal(tmp_path / "crashy.journal")
    entry = state.streams["s"]
    assert entry.delivered == [0, 1, 2]
    assert entry.undelivered == [3, 4]

    p2 = serving(runtime, "survivor", tmp_path, busy_ms=1.0)
    got = []
    topic = f"{runtime.topic_path_process}/test/replay"
    runtime.add_message_handler(
        lambda _topic, payload: got.append(payload), topic)
    assert p2.adopt("crashy", topic) == 1
    run_until(runtime, lambda: len(got) == 2, timeout=10.0)
    frame_ids = sorted(int(dict(parse(payload)[1][0])["frame_id"])
                       for payload in got)
    assert frame_ids == [3, 4]          # exactly the undelivered set
    p2.stop()


# -- gateway idle-session reaping -------------------------------------------

@pytest.mark.slow
def test_multi_process_chaos_driver_kill():
    """Full-fidelity chaos walk: real processes, a real SIGKILL, the
    native TCP MQTT broker -- the LWT/adoption path with no loopback
    shortcuts.  (tier-1 runs the in-process twin above.)"""
    from aiko_services_tpu.faults.chaos import run_chaos
    result = run_chaos(frames=8, busy_ms=40.0,
                       echo=lambda *_args: None)
    assert result["ok"], result
    assert result["failovers"] >= 1
    assert result["dropped"] == 0


@pytest.mark.slow
def test_multi_process_chaos_driver_rolling():
    from aiko_services_tpu.faults.chaos import run_chaos
    result = run_chaos(frames=12, mode="rolling", busy_ms=40.0,
                       echo=lambda *_args: None)
    assert result["ok"], result
    assert result["dropped"] == 0


def test_idle_session_reaped_frees_stream_and_budget(runtime):
    """A client that vanishes without a FIN (no frames, no pongs) is
    reaped after ``session_idle_ms``: its stream, window slots and
    QoS in-flight budget come back instead of leaking to process
    exit."""
    pipeline = Pipeline(
        {"version": 0, "name": "gwidle", "runtime": "jax",
         "graph": ["(work)"],
         "parameters": {"gateway": "on", "session_idle_ms": 250},
         "elements": [stage("work")]}, runtime=runtime)
    gateway = pipeline.gateway
    assert gateway.session_idle_ms == 250.0

    client = GatewayClient("127.0.0.1", gateway.port, timeout=30.0)

    def open_then_vanish():
        client.open(session="ghost", tenant="t1")
        # ... and never read again: no pong ever answers the ping

    thread, box = in_thread(open_then_vanish)
    finish(runtime, thread, box)
    run_until(runtime, lambda: len(pipeline.streams) == 1,
              timeout=10.0)
    assert gateway.session_count() == 1

    run_until(runtime,
              lambda: gateway.sessions_reaped == 1
              and len(pipeline.streams) == 0, timeout=10.0)
    assert gateway.session_count() == 0
    # a LIVE client (pongs answered by the codec in recv) is NOT
    # reaped across the same window
    live = GatewayClient("127.0.0.1", gateway.port, timeout=30.0)

    def stay_alive():
        live.open(session="alive", tenant="t1")
        deadline = time.monotonic() + 0.6
        while time.monotonic() < deadline:
            try:
                live.recv(timeout=0.1)  # answers pings in line
            except Exception:
                pass
        live.close()

    thread, box = in_thread(stay_alive)
    finish(runtime, thread, box)
    assert gateway.sessions_reaped == 1     # still only the ghost
    pipeline.stop()
