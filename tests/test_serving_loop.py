"""Device-resident LLM serving loop (ISSUE 8): decode_loop
equivalence against the host loop, paged KV cache invariants,
speculative multi-token decoding, and replay-from-last-emitted-block
recovery.

The equivalence contract: at temperature 0 the device loop emits
TOKEN-IDENTICAL streams to the host loop for the same prompts -- the
loop's on-device stop detection mirrors the host finish test exactly
and may only run LONGER (overshoot is truncated at retire).  Plain and
paged loops share the host loop's decode math bit-for-bit, so bf16 is
exact there; the speculative verify step attends through a different
(concat) path whose bf16 argmax can flip on near-ties, so the
speculation contract is pinned in float32 where the math is exact.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiko_services_tpu.models import llama, ContinuousBatcher, Request
from aiko_services_tpu.models.paged import (PageAllocator, gather_slot,
                                            init_paged_cache,
                                            pages_per_slot)
from aiko_services_tpu.models.tokenizer import ByteTokenizer
from aiko_services_tpu.pipeline.overlap import TransferLedger


@pytest.fixture(scope="module")
def tiny():
    config = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), config)
    return config, params


@pytest.fixture(scope="module")
def tiny_f32():
    config = dataclasses.replace(llama.LlamaConfig.tiny(),
                                 dtype="float32")
    params = llama.init_params(jax.random.PRNGKey(0), config)
    return config, params


def _run(params, config, n_requests=6, max_new=9, max_steps=800,
         prompts=None, **kw):
    """Drain ``n_requests`` greedy requests through one batcher ->
    ({request_id: [tokens]}, batcher)."""
    tok = ByteTokenizer()
    emitted = {}

    def emit(request_id, token, finished):
        emitted.setdefault(request_id, []).append(token)

    batcher = ContinuousBatcher(params, config, max_slots=4, max_seq=64,
                                prefill_chunk=16, **kw)
    for i in range(n_requests):
        text = prompts[i] if prompts else f"hello world {i}"
        batcher.submit(Request(request_id=f"r{i}",
                               prompt_tokens=tok.encode(text),
                               max_new_tokens=max_new, emit=emit))
    steps = batcher.run_until_drained(max_steps=max_steps)
    assert steps < max_steps
    return emitted, batcher


# -- equivalence: device loop == host loop at temperature 0 ----------------


def test_device_loop_matches_host_loop(tiny):
    """ISSUE 8 acceptance: the lax.while_loop serving path is
    token-identical to the per-token host loop (bf16: same decode
    math, same argmax)."""
    config, params = tiny
    host, _ = _run(params, config)
    loop, batcher = _run(params, config, decode_block_tokens=8)
    assert host == loop
    assert batcher.blocks_dispatched >= 1
    assert batcher.blocks_retired == batcher.blocks_dispatched
    # The loop batches up to ring tokens PER SLOT per dispatch: far
    # fewer host round trips than tokens emitted.
    assert batcher.blocks_retired < batcher.tokens_emitted / 4


def test_device_loop_paged_matches_host_loop(tiny):
    """Page-table gather/scatter equals the dense cache path
    token-for-token (the paged half of the equivalence criterion)."""
    config, params = tiny
    host, _ = _run(params, config)
    paged, batcher = _run(params, config, decode_block_tokens=8,
                          kv_page_tokens=16)
    assert host == paged
    assert batcher._pages is not None


def test_device_loop_int8_kv_matches_host_loop(tiny):
    """int8 KV (per-token scales) through the device loop and the
    paged pool equals the host loop's int8 path token-for-token."""
    config, params = tiny
    config8 = dataclasses.replace(config, kv_dtype="int8")
    host, _ = _run(params, config8)
    loop, _ = _run(params, config8, decode_block_tokens=8)
    assert host == loop
    paged, _ = _run(params, config8, decode_block_tokens=8,
                    kv_page_tokens=16)
    assert host == paged


def test_device_loop_chains_blocks_inflight(tiny):
    """inflight > 1 keeps several loop blocks chained device-side;
    retire order preserves the emitted stream exactly."""
    config, params = tiny
    host, _ = _run(params, config, max_new=17)
    loop, batcher = _run(params, config, max_new=17,
                         decode_block_tokens=4, inflight=3)
    assert host == loop
    assert batcher.blocks_retired >= 4


def test_device_loop_respects_eos(tiny):
    """On-device EOS detection stops a row exactly where the host
    finish test does, including an EOS landing on the FIRST token."""
    config, params = tiny
    tok = ByteTokenizer()

    def run(eos, **kw):
        emitted = {}

        def emit(request_id, token, finished):
            emitted.setdefault(request_id, []).append((token, finished))

        batcher = ContinuousBatcher(params, config, max_slots=2,
                                    max_seq=64, prefill_chunk=16, **kw)
        for i in range(3):
            batcher.submit(Request(
                request_id=f"r{i}", prompt_tokens=tok.encode(f"eos {i}"),
                max_new_tokens=12, eos_tokens=eos, emit=emit))
        assert batcher.run_until_drained(max_steps=800) < 800
        return emitted

    reference = run(())
    # Pick each stream's 3rd token as its stop set: the device loop
    # must cut exactly there, finished flag on the stop token.
    eos = tuple({tokens[2][0] for tokens in reference.values()})
    host = run(eos)
    loop = run(eos, decode_block_tokens=8)
    assert host == loop
    for tokens in loop.values():
        assert tokens[-1][1] is True
        assert len(tokens) <= 12


# -- speculative decoding --------------------------------------------------


@pytest.mark.parametrize("mode", ["ngram", "draft"])
@pytest.mark.parametrize("paged", [0, 16])
def test_speculative_matches_host_loop_f32(tiny_f32, mode, paged):
    """Lossless speculation: greedy rows accept only verified-matching
    drafts, so the emitted stream is token-identical to the host loop
    (float32: the verify chunk's concat attention is exact there)."""
    config, params = tiny_f32
    host, _ = _run(params, config)
    spec, batcher = _run(params, config, decode_block_tokens=8,
                         speculative=mode, kv_page_tokens=paged)
    assert host == spec
    assert batcher.draft_tokens > 0


def test_draft_speculation_accepts_tokens(tiny_f32):
    """The int8 self-draft agrees with its own target often enough to
    accept a useful fraction (the speculation win exists at all)."""
    config, params = tiny_f32
    _, batcher = _run(params, config, decode_block_tokens=8,
                      speculative="draft")
    assert batcher.accepted_tokens > 0
    assert batcher.accepted_tokens <= batcher.draft_tokens


def test_speculative_requires_device_loop(tiny):
    config, params = tiny
    with pytest.raises(ValueError, match="device loop"):
        ContinuousBatcher(params, config, speculative="ngram")
    with pytest.raises(ValueError, match="off|ngram|draft"):
        ContinuousBatcher(params, config, decode_block_tokens=8,
                          speculative="banana")
    # A ring too small for one worst-case speculative emission would
    # dispatch blocks that run zero loop iterations (a silent
    # no-progress wedge): refused at construction.
    with pytest.raises(ValueError, match="speculative emission"):
        ContinuousBatcher(params, config, decode_block_tokens=4,
                          speculative="ngram", spec_tokens=4)


# -- paged KV cache invariants ---------------------------------------------


def test_page_allocator_units():
    alloc = PageAllocator(total_pages=9, pages_per_slot=4, max_slots=3)
    assert alloc.free_pages == 8                 # page 0 is trash
    assert alloc.pages_for(0, 16) == 0
    assert alloc.pages_for(1, 16) == 1
    assert alloc.pages_for(17, 16) == 2
    assert alloc.pages_for(10_000, 16) == 4      # clamped to pps
    assert alloc.ensure(0, 2) and alloc.holds(0) == 2
    assert alloc.dirty[0][:2] != [0, 0]
    assert alloc.ensure(0, 2)                    # idempotent
    assert alloc.missing(0, 4) == 2
    assert alloc.ensure(1, 4) and alloc.ensure(2, 2)
    assert alloc.free_pages == 0
    # Atomic failure: nothing allocated, nothing dirtied.
    alloc.dirty.clear()
    assert not alloc.ensure(0, 4)
    assert alloc.holds(0) == 2 and not alloc.dirty
    assert alloc.release(1) == 4
    assert alloc.free_pages == 4
    assert alloc.dirty[1] == [0] * 4             # row reset to trash
    assert alloc.ensure(0, 4)
    alloc.reset()
    assert alloc.free_pages == 8 and alloc.holds(0) == 0


def test_paged_prefill_matches_dense(tiny_f32):
    """prefill_into_slot through a page table produces the same logits
    AND the same cache bytes (gathered) as the dense path."""
    config, params = tiny_f32
    tokens = jax.random.randint(jax.random.PRNGKey(3), (1, 16), 0,
                                config.vocab_size)
    dense = llama.init_cache(config, 2, 32)
    logits_d, dense = llama.prefill_into_slot(
        params, config, tokens, dense, slot=1,
        start=jnp.int32(0))
    paged = init_paged_cache(config, 2, 32, page_tokens=8)
    table = paged["page_table"].at[1].set(jnp.arange(1, 5))
    paged["page_table"] = table
    logits_p, paged = llama.prefill_into_slot(
        params, config, tokens, paged, slot=1,
        start=jnp.int32(0))
    np.testing.assert_array_equal(np.asarray(logits_d),
                                  np.asarray(logits_p))
    # gather_slot works on one layer's pool view (the layer scan's
    # perspective); compare each layer's gathered row to the dense row.
    for layer in range(config.n_layers):
        row_d = np.asarray(dense["k"][layer, 1])           # [T, K*hd]
        row_p = np.asarray(gather_slot(paged["k"][layer],
                                       paged["page_table"][1])[0])
        np.testing.assert_array_equal(row_d[:16], row_p[:16])


def test_pool_pressure_preempts_youngest_and_resumes(tiny_f32):
    """An under-provisioned pool preempts the YOUNGEST slot; its
    generation resumes from committed tokens and every request still
    emits the exact host-loop stream (nothing dropped or re-emitted)."""
    config, params = tiny_f32
    host, _ = _run(params, config, n_requests=4, max_new=24)
    # Each request wants ~3 pages (prompt + 24 new tokens); 4 slots
    # want 12, the pool holds 8 usable -- guaranteed preemption churn.
    pressed, batcher = _run(params, config, n_requests=4, max_new=24,
                            max_steps=3000, decode_block_tokens=4,
                            kv_page_tokens=16, kv_pages=9)
    assert host == pressed
    assert batcher.evictions >= 1
    assert batcher._pages.free_pages >= 0


def test_admit_evict_keeps_untouched_slot_bytes_identical(tiny_f32):
    """Mid-generation admissions and pool-pressure evictions of OTHER
    slots never touch a live slot's cache bytes (the page-table
    isolation invariant)."""
    config, params = tiny_f32
    tok = ByteTokenizer()
    emitted = {}

    def emit(request_id, token, finished):
        emitted.setdefault(request_id, []).append(token)

    batcher = ContinuousBatcher(params, config, max_slots=3, max_seq=64,
                                prefill_chunk=16, decode_block_tokens=4,
                                inflight=1, kv_page_tokens=16,
                                kv_pages=7)
    batcher.submit(Request(request_id="r0",
                           prompt_tokens=tok.encode("long runner"),
                           max_new_tokens=40, emit=emit))
    while len(emitted.get("r0", ())) < 6:
        batcher.step()
    assert batcher.blocks_in_flight == 0         # inflight=1 quiesces
    slot = batcher.slots.index(
        next(r for r in batcher.slots if r is not None))
    valid = int(batcher.lengths[slot])

    def snapshot():
        table_row = batcher.cache["page_table"][slot]
        k = np.stack([np.asarray(gather_slot(batcher.cache["k"][layer],
                                             table_row)[0])[:valid]
                      for layer in range(config.n_layers)])
        v = np.stack([np.asarray(gather_slot(batcher.cache["v"][layer],
                                             table_row)[0])[:valid]
                      for layer in range(config.n_layers)])
        return k, v

    before = snapshot()
    # Two more long requests under a ~2-slot pool: admissions write
    # neighboring pages and pressure preempts the youngest.
    for i in (1, 2):
        batcher.submit(Request(
            request_id=f"r{i}", prompt_tokens=tok.encode(f"rival {i}"),
            max_new_tokens=24, emit=emit))
    for _ in range(5):
        batcher.step()
    assert batcher.slots[slot] is not None       # r0 was never evicted
    assert batcher.slots[slot].request_id == "r0"
    # The churn was real: another request occupies a slot (or was
    # already preempted for pages).
    assert batcher.evictions or any(
        r is not None and r.request_id != "r0" for r in batcher.slots)
    after = snapshot()
    np.testing.assert_array_equal(before[0], after[0])
    np.testing.assert_array_equal(before[1], after[1])
    assert batcher.run_until_drained(max_steps=3000) < 3000
    assert all(len(tokens) in (40, 24) for tokens in emitted.values())


def test_pressure_eviction_of_joining_slot_during_dispatch(tiny_f32):
    """Regression: the dispatch's page-ensure loop can preempt a
    JUST-ADMITTED slot (the youngest occupant) for pages -- the fold-in
    must re-snapshot the joining list instead of popping the evicted
    slot's _pending_first entry (KeyError before the fix).  All
    requests still emit the exact host-loop streams."""
    config, params = tiny_f32
    host, _ = _run(params, config, n_requests=4, max_new=12)
    # 5 usable pages: four one-page admissions burst in together, then
    # the dispatch ensure (2 pages per slot) must evict a joining slot.
    pressed, batcher = _run(params, config, n_requests=4, max_new=12,
                            max_steps=3000, decode_block_tokens=8,
                            kv_page_tokens=16, kv_pages=6)
    assert host == pressed
    assert batcher.evictions >= 1


def test_pressure_eviction_during_batched_admission(tiny_f32):
    """Regression: a multi-chunk admission burst under pool pressure
    can preempt a slot that is itself admitting (still in the prefill
    queue or already collected into the batched dispatch) -- the tick
    must drop evicted slots instead of crashing (IndexError /
    AttributeError before the fix), and every request still emits the
    exact host-loop stream."""
    config, params = tiny_f32
    prompts = ["abcdefghijklmnopqrstuvwx" + str(i) for i in range(4)]
    host, _ = _run(params, config, n_requests=4, max_new=8,
                   prompts=prompts)
    pressed, batcher = _run(params, config, n_requests=4, max_new=8,
                            max_steps=3000, prompts=prompts,
                            decode_block_tokens=8, kv_page_tokens=16,
                            kv_pages=5)
    assert host == pressed
    assert batcher.evictions >= 1


def test_pressure_eviction_during_sync_decode_tick(tiny_f32):
    """Regression: the synchronous decode path (decode_block == 1,
    paged) crossing a page boundary can preempt the OTHER decoding
    slot -- the tick must refresh its slot list instead of emitting
    into the evicted slot's None request (AttributeError before the
    fix)."""
    config, params = tiny_f32
    prompts = ["page walker", "page rival"]
    host, _ = _run(params, config, n_requests=2, max_new=24,
                   prompts=prompts)
    pressed, batcher = _run(params, config, n_requests=2, max_new=24,
                            max_steps=3000, prompts=prompts,
                            kv_page_tokens=16, kv_pages=5)
    assert host == pressed
    assert batcher.evictions >= 1


# -- recovery: replay from the last emitted block --------------------------


def test_recover_resumes_from_last_emitted_block(tiny):
    """A device loss mid-generation (fault probe raising at dispatch,
    standing in for a dying chip's XLA error): recover() re-queues
    every live request at its committed prefix, and the drained stream
    is token-identical to an unfaulted run -- nothing lost, nothing
    re-emitted."""
    config, params = tiny
    host, _ = _run(params, config, max_new=13)

    tok = ByteTokenizer()
    emitted = {}

    def emit(request_id, token, finished):
        emitted.setdefault(request_id, []).append(token)

    fired = {"n": 0}

    def probe(point):
        assert point == "decode_block"
        fired["n"] += 1
        if fired["n"] == 3:                      # blocks already retired
            raise RuntimeError("injected chip death")

    batcher = ContinuousBatcher(params, config, max_slots=4, max_seq=64,
                                prefill_chunk=16, decode_block_tokens=4,
                                inflight=1, fault_probe=probe)
    for i in range(6):
        batcher.submit(Request(
            request_id=f"r{i}",
            prompt_tokens=tok.encode(f"hello world {i}"),
            max_new_tokens=13, emit=emit))
    steps = 0
    while (batcher.pending or batcher.active_count
           or batcher.blocks_in_flight) and steps < 2000:
        try:
            batcher.step()
        except RuntimeError:
            revived = batcher.recover()
            assert revived >= 1
        steps += 1
    assert steps < 2000
    assert emitted == host
    assert batcher.recoveries == 1
    assert fired["n"] > 3                        # generation continued


def test_recover_paged_speculative(tiny_f32):
    """recover() rebuilds the page pool and speculation state too."""
    config, params = tiny_f32
    host, _ = _run(params, config, max_new=11)

    tok = ByteTokenizer()
    emitted = {}

    def emit(request_id, token, finished):
        emitted.setdefault(request_id, []).append(token)

    boom = {"armed": False}

    def probe(point):
        if boom["armed"]:
            boom["armed"] = False
            raise RuntimeError("injected chip death")

    batcher = ContinuousBatcher(params, config, max_slots=4, max_seq=64,
                                prefill_chunk=16, decode_block_tokens=8,
                                inflight=1, speculative="ngram",
                                kv_page_tokens=16, fault_probe=probe)
    for i in range(6):
        batcher.submit(Request(
            request_id=f"r{i}",
            prompt_tokens=tok.encode(f"hello world {i}"),
            max_new_tokens=11, emit=emit))
    steps = 0
    while (batcher.pending or batcher.active_count
           or batcher.blocks_in_flight) and steps < 2000:
        if steps == 6:
            boom["armed"] = True
        try:
            batcher.step()
        except RuntimeError:
            batcher.recover()
        steps += 1
    assert steps < 2000
    assert emitted == host
    assert batcher.recoveries == 1


# -- shared-prefix KV: COW page sharing (ISSUE 18) -------------------------


_SHARED_PREFIX = [3 + (i % 40) for i in range(32)]     # 2 whole pages


def _drive_prefix(params, config, prompts, max_new=8,
                  serial_first=False, **kw):
    """Drain token-list prompts through one batcher ->
    ({request_id: [tokens]}, batcher).  ``serial_first`` drains the
    first request alone (priming the prefix index) before the rest."""
    emitted = {}

    def emit(request_id, token, finished):
        emitted.setdefault(request_id, []).append(token)

    defaults = dict(max_slots=4, max_seq=64, prefill_chunk=16,
                    decode_block_tokens=8, kv_page_tokens=16,
                    prefix_cache=True, prefix_min_tokens=16)
    defaults.update(kw)
    batcher = ContinuousBatcher(params, config, **defaults)
    for i, prompt in enumerate(prompts):
        batcher.submit(Request(request_id=f"r{i}",
                               prompt_tokens=list(prompt),
                               max_new_tokens=max_new, emit=emit))
        if serial_first and i == 0:
            assert batcher.run_until_drained(max_steps=3000) < 3000
    assert batcher.run_until_drained(max_steps=3000) < 3000
    return emitted, batcher


def test_prefix_cache_warm_matches_cold(tiny_f32):
    """The tentpole equivalence contract: a request admitted onto
    SHARED prefix pages (prefill skipped for the whole shared span)
    emits the exact token stream of an unshared cold prefill, the
    index serves the warm request (hits recorded), and no page leaks."""
    config, params = tiny_f32
    prompts = [_SHARED_PREFIX + [100 + i, 50 + i, 7, 11 + i, 2, 9, 4, 1]
               for i in range(3)]
    cold, cold_b = _drive_prefix(params, config, prompts,
                                 serial_first=True, prefix_cache=False)
    warm, warm_b = _drive_prefix(params, config, prompts,
                                 serial_first=True)
    assert cold == warm
    # r0 primes the index; r1/r2 adopt both shared pages each.
    assert warm_b.prefix_hits >= 4
    assert warm_b.prefix_shared_tokens >= 64
    assert warm_b.prefix_hit_rate() > 0.0
    assert cold_b.prefix_hits == 0            # off = no index traffic
    assert warm_b._pages.leaked_pages() == 0
    assert cold_b._pages.leaked_pages() == 0


def test_prefix_divergence_cow_leaves_donor_untouched(tiny_f32):
    """COW at the divergence point: the adopter maps the donor's
    shared pages PHYSICALLY (same table entries), allocates a fresh
    page where the prompts diverge, and the donor's cache bytes over
    the shared span stay bit-identical while both keep generating."""
    config, params = tiny_f32
    pA = _SHARED_PREFIX + [100 + i for i in range(8)]
    pB = _SHARED_PREFIX + [70 + i for i in range(8)]
    emitted = {}

    def emit(request_id, token, finished):
        emitted.setdefault(request_id, []).append(token)

    batcher = ContinuousBatcher(params, config, max_slots=3, max_seq=64,
                                prefill_chunk=16, decode_block_tokens=4,
                                inflight=1, kv_page_tokens=16,
                                prefix_cache=True, prefix_min_tokens=16)
    batcher.submit(Request(request_id="A", prompt_tokens=list(pA),
                           max_new_tokens=20, emit=emit))
    while len(emitted.get("A", ())) < 4:
        batcher.step()
    assert batcher.blocks_in_flight == 0         # inflight=1 quiesces
    slot_a = next(i for i, r in enumerate(batcher.slots)
                  if r is not None and r.request_id == "A")

    def snapshot():
        row = batcher.cache["page_table"][slot_a]
        k = np.stack([np.asarray(gather_slot(batcher.cache["k"][layer],
                                             row)[0])[:32]
                      for layer in range(config.n_layers)])
        v = np.stack([np.asarray(gather_slot(batcher.cache["v"][layer],
                                             row)[0])[:32]
                      for layer in range(config.n_layers)])
        return k, v

    before = snapshot()
    batcher.submit(Request(request_id="B", prompt_tokens=list(pB),
                           max_new_tokens=6, emit=emit))
    slot_b = None
    for _ in range(100):
        batcher.step()
        slot_b = next((i for i, r in enumerate(batcher.slots)
                       if r is not None and r.request_id == "B"), None)
        if slot_b is not None:
            break
    assert slot_b is not None
    table = np.asarray(jax.device_get(batcher.cache["page_table"]))
    # the shared span is the SAME physical pages; the divergent page
    # (logical 2, where the prompts' tails differ) is a fresh copy.
    np.testing.assert_array_equal(table[slot_a][:2], table[slot_b][:2])
    assert table[slot_b][2] not in (0, table[slot_a][2])
    while len(emitted.get("B", ())) < 6:         # B finishes; A lives
        batcher.step()
    assert batcher.slots[slot_a] is not None
    assert batcher.slots[slot_a].request_id == "A"
    after = snapshot()
    np.testing.assert_array_equal(before[0], after[0])
    np.testing.assert_array_equal(before[1], after[1])
    assert batcher.run_until_drained(max_steps=2000) < 2000
    # B's stream equals an unshared run of the same prompts.
    cold, _ = _drive_prefix(params, config, [pA, pB],
                            max_new=6, prefix_cache=False,
                            decode_block_tokens=4, inflight=1,
                            max_slots=3)
    assert emitted["B"] == cold["r1"]
    assert batcher._pages.leaked_pages() == 0


def test_prefix_cache_refcounts_survive_eviction_and_recover(tiny_f32):
    """Refcounts reach zero on every exit path: pool-pressure
    eviction of shared-prefix requests, stream drain, and a full
    recover() all leave zero leaked pages -- and the pressured shared
    run still emits the exact unshared streams."""
    config, params = tiny_f32
    prompts = [_SHARED_PREFIX + [120 + i, 8, 90 + i, 5, 60 + i, 3,
                                 40 + i, 2] for i in range(4)]
    cold, _ = _drive_prefix(params, config, prompts, max_new=24,
                            serial_first=True, prefix_cache=False)
    pressed, batcher = _drive_prefix(params, config, prompts,
                                     max_new=24, serial_first=True,
                                     decode_block_tokens=4,
                                     kv_pages=8)
    assert cold == pressed
    assert batcher.evictions >= 1
    assert batcher._pages.leaked_pages() == 0
    batcher.recover()                            # cold cache, no leaks
    assert batcher._pages.leaked_pages() == 0
    assert batcher._pages.free_pages == batcher._pages.total - 1
    assert batcher._pages.stats["prefix_pages"] == 0


def test_prefix_chaos_kill_and_journal_adoption_no_leaks(tiny_f32):
    """The chaos walk of the acceptance criteria: a ``decode_block``
    kill mid-generation over SHARED pages, recover(), then journal
    adoption (``resume_request``) of a shared-prefix request -- the
    adopted request rides the re-registered index, emits exactly its
    remaining budget, and the pool ends with zero leaked pages."""
    config, params = tiny_f32
    prompts = [_SHARED_PREFIX + [100 + i, 9, 80 + i, 6, 30 + i, 1,
                                 20 + i, 4] for i in range(4)]
    emitted = {}

    def emit(request_id, token, finished):
        emitted.setdefault(request_id, []).append(token)

    fired = {"n": 0}

    def probe(point):
        assert point == "decode_block"
        fired["n"] += 1
        if fired["n"] == 3:
            raise RuntimeError("injected chip death")

    batcher = ContinuousBatcher(params, config, max_slots=4, max_seq=64,
                                prefill_chunk=16, decode_block_tokens=4,
                                inflight=1, kv_page_tokens=16,
                                prefix_cache=True, prefix_min_tokens=16,
                                fault_probe=probe)
    for i, prompt in enumerate(prompts):
        batcher.submit(Request(request_id=f"r{i}",
                               prompt_tokens=list(prompt),
                               max_new_tokens=10, emit=emit))
    steps = 0
    while (batcher.pending or batcher.active_count
           or batcher.blocks_in_flight) and steps < 3000:
        try:
            batcher.step()
        except RuntimeError:
            assert batcher.recover() >= 1        # refcounts reset too
            assert batcher._pages.leaked_pages() == 0
        steps += 1
    assert steps < 3000 and batcher.recoveries == 1
    host, _ = _drive_prefix(params, config, prompts, max_new=10,
                            prefix_cache=False, kv_page_tokens=0)
    assert emitted == host                       # kill lost nothing
    # journal adoption: a peer's shared-prefix request resumes at its
    # committed prefix and generates only the remaining budget.
    adopted = Request(request_id="adopted",
                      prompt_tokens=_SHARED_PREFIX + [100, 9, 80, 6,
                                                      30, 1, 20, 4],
                      max_new_tokens=10, emit=emit)
    batcher.submit(adopted)
    committed = host["r0"][:4]
    assert batcher.resume_request(adopted, committed)
    assert batcher.run_until_drained(max_steps=2000) < 2000
    assert emitted["adopted"] == host["r0"][4:]
    assert batcher.prefix_hits >= 1              # rode the warm index
    assert batcher._pages.leaked_pages() == 0


def test_prefix_page_allocator_units():
    """Allocator-level arithmetic for the prefix index: hash-chain
    agreement, match capped one page short, adoption refcounts,
    release keeping indexed pages warm, and leaf-first reclaim under
    pool pressure."""
    from aiko_services_tpu.models.paged import prefix_page_keys

    tokens = list(range(40))
    keys = prefix_page_keys(tokens, 16)
    assert len(keys) == 2                        # whole pages only
    assert prefix_page_keys(tokens[:32], 16) == keys
    divergent = tokens[:16] + [999] * 24
    other = prefix_page_keys(divergent, 16)
    assert other[0] == keys[0] and other[1] != keys[1]

    alloc = PageAllocator(total_pages=9, pages_per_slot=4, max_slots=3,
                          prefix_cache=True, prefix_min_tokens=16)
    assert alloc.match_prefix(tokens, 16) == 0   # nothing indexed yet
    assert alloc.ensure(0, 3)
    alloc.register_prefix(0, tokens, 40, 16)     # indexes 2 pages
    assert alloc.match_prefix(tokens, 16) == 2
    assert alloc.match_prefix(tokens[:33], 16) == 2
    assert alloc.match_prefix(tokens[:32], 16) == 1   # 1 token must
    assert alloc.match_prefix(divergent, 16) == 1  # . . . prefill
    assert alloc.match_prefix(tokens[:8], 16) == 0    # below minimum
    assert alloc.adopt_prefix(1, tokens, 16) == 32
    assert alloc.holds(1) == 2 and alloc.prefix_hits == 2
    # donor release: indexed pages stay warm (index ref), the
    # unregistered third page frees; adopter release drops to
    # index-only; nothing leaks at any point.
    assert alloc.release(0) == 3
    assert alloc.match_prefix(tokens, 16) == 2
    assert alloc.leaked_pages() == 0
    assert alloc.release(1) == 2
    assert alloc.match_prefix(tokens, 16) == 2   # still warm
    assert alloc.leaked_pages() == 0
    # pool pressure reclaims the index-only pages (leaf first) rather
    # than failing the allocation.
    assert alloc.ensure(2, 4)
    assert alloc.ensure(0, 4)
    assert alloc.match_prefix(tokens, 16) == 0   # index reclaimed
    assert alloc.leaked_pages() == 0
    alloc.reset()
    assert alloc.free_pages == 8 and alloc.leaked_pages() == 0


# -- the one-counted-fetch-per-block serving contract ----------------------


def test_one_labeled_ledger_fetch_per_retired_block(tiny):
    """The device-resident swag contract for serving: every retired
    loop block pays exactly ONE explicit ledger fetch (label
    ``llm_block``), and the ledger sees no other explicit fetches from
    the decode path."""
    config, params = tiny
    ledger = TransferLedger(policy="log")
    _, batcher = _run(params, config, decode_block_tokens=8,
                      fetch=lambda tree: ledger.fetch(tree,
                                                      label="llm_block"))
    assert batcher.blocks_retired >= 1
    stats = ledger.stats
    assert stats["explicit_by_label"]["llm_block"] \
        == batcher.blocks_retired
    assert stats["explicit"] == batcher.blocks_retired


# -- through the pipeline element ------------------------------------------


def _llm_definition(name, parameters, pipeline_parameters=None):
    return {
        "version": 0, "name": name, "runtime": "jax",
        "parameters": pipeline_parameters or {},
        "graph": ["(llm)"],
        "elements": [{
            "name": "llm",
            "input": [{"name": "text"}],
            "output": [{"name": "text"}],
            "parameters": {"max_new_tokens": 8, "max_seq": 64,
                           **parameters},
            "deploy": {"local": {
                "module": "aiko_services_tpu.elements.llm",
                "class_name": "LLM"}}}]}


def _pipe_generate(runtime, definition, prompts):
    import queue

    from aiko_services_tpu.pipeline import Pipeline
    from conftest import run_until

    responses = queue.Queue()
    pipeline = Pipeline(definition, runtime=runtime)
    stream = pipeline.create_stream_local("1", queue_response=responses)
    for text in prompts:
        pipeline.create_frame_local(stream, {"text": text})
    assert run_until(runtime, lambda: responses.qsize() >= len(prompts),
                     timeout=120.0)
    texts = []
    while not responses.empty():
        _, _, swag, _, okay, diagnostic = responses.get()
        assert okay, diagnostic
        texts.append(swag["text"])
    return sorted(texts), pipeline


def test_llm_element_device_loop_end_to_end(runtime):
    """The serving contract through a real pipeline under
    ``transfer_guard: disallow``: device-loop generation completes,
    emits the same text as the host loop, and the transfer ledger
    counts EXACTLY one labeled fetch per retired block."""
    prompts = ["hello there", "general kenobi"]
    host, host_pipe = _pipe_generate(
        runtime, _llm_definition("llm_host", {}), prompts)
    host_pipe.stop()
    loop, pipeline = _pipe_generate(
        runtime, _llm_definition(
            "llm_loop",
            {"decode_block_tokens": 4, "kv_page_tokens": 16},
            pipeline_parameters={"transfer_guard": "disallow"}),
        prompts)
    assert loop == host
    batcher = pipeline.graph.get_node("llm").element._batcher
    assert batcher.device_loop and batcher.blocks_retired >= 1
    stats = pipeline.transfer_stats()
    assert stats["explicit_by_label"]["llm_block"] \
        == batcher.blocks_retired
    assert stats["implicit"] == 0
    # Serving latency histograms reached the telemetry plane.  The
    # worker publishes AFTER the tick that finishes the last request,
    # racing the frame response this test just consumed -- wait for
    # the publish instead of sampling once (flaky before).
    from conftest import run_until
    assert run_until(runtime,
                     lambda: "llm_ttft_ms" in pipeline.metrics_text())
    metrics = pipeline.metrics_text()
    assert "llm_ttft_ms" in metrics
    assert "llm_tpot_ms" in metrics
    pipeline.stop()


def test_llm_element_speculative_telemetry(runtime):
    """Speculation counters flow to metrics_text() and share keys."""
    from conftest import run_until

    texts, pipeline = _pipe_generate(
        runtime, _llm_definition(
            "llm_spec",
            {"decode_block_tokens": 8, "speculative": "ngram"}),
        ["anaphora anaphora"])
    assert texts and isinstance(texts[0], str)
    batcher = pipeline.graph.get_node("llm").element._batcher
    assert batcher.draft_tokens > 0
    metrics = pipeline.metrics_text()
    assert "llm_draft_tokens" in metrics
    assert run_until(
        runtime,
        lambda: pipeline.share.get("llm_draft_tokens")
        == batcher.draft_tokens, timeout=10.0)
    assert pipeline.share.get("llm_accepted_tokens") \
        == batcher.accepted_tokens
    pipeline.stop()


def test_llm_element_rejects_bad_mode_at_create(runtime):
    """The ELEMENT_PARAMETERS domain check (analysis/params.py) fails
    a typo'd speculative mode at CREATE time, not at frame N."""
    from aiko_services_tpu.pipeline import DefinitionError, Pipeline

    with pytest.raises(DefinitionError, match="off|ngram|draft"):
        Pipeline(_llm_definition("llm_bad", {"speculative": "banana"}),
                 runtime=runtime)
