"""LLM serving: LLMService actor (streamed concurrent generation over the
fabric, continuous batching) and the LLM pipeline element."""

import json

from conftest import run_until

from aiko_services_tpu.elements import LLMService
from aiko_services_tpu.models import llama
from aiko_services_tpu.pipeline import create_pipeline
from aiko_services_tpu.services import get_service_proxy


def _tiny_service(runtime, max_slots=4):
    config = llama.LlamaConfig.tiny(vocab_size=512, max_seq=128)
    return LLMService(runtime=runtime, config=config,
                      max_slots=max_slots)


def test_llm_service_streams_concurrent_requests(runtime):
    service = _tiny_service(runtime)
    proxy = get_service_proxy(runtime, service.topic_path)

    events = {"a": [], "b": []}
    response_topic = f"{runtime.topic_path_process}/llm_test"

    def on_reply(topic, payload):
        from aiko_services_tpu.utils import parse
        command, parameters = parse(payload)
        events[parameters[0]].append((command, parameters))

    runtime.add_message_handler(on_reply, response_topic)
    proxy.generate(response_topic, "a", "hello", 8, 0)
    proxy.generate(response_topic, "b", "world", 8, 0)

    assert run_until(
        runtime,
        lambda: any(c == "complete" for c, _ in events["a"])
        and any(c == "complete" for c, _ in events["b"]),
        timeout=30.0)
    # Streaming: token fragments preceded completion for both requests.
    for rid in ("a", "b"):
        commands = [c for c, _ in events[rid]]
        assert commands.count("token") >= 1
        assert commands[-1] == "complete"
    # Both decoded together through the shared batcher.
    assert service.batcher.tokens_emitted >= 16
    assert service.share["tokens_emitted"] >= 16


def test_llm_service_generate_local_deterministic(runtime):
    service = _tiny_service(runtime)
    first = service.generate_local("abc", max_new_tokens=6)
    second = service.generate_local("abc", max_new_tokens=6)
    assert first == second            # greedy decoding is deterministic


def test_llm_pipeline_element(runtime, tmp_path):
    definition = {
        "version": 0, "name": "llm_pipe", "runtime": "jax",
        "graph": ["(llm)"],
        "elements": [{
            "name": "llm",
            "input": [{"name": "text"}],
            "output": [{"name": "text"}],
            "parameters": {"max_new_tokens": 4, "max_seq": 64},
            "deploy": {"local": {
                "module": "aiko_services_tpu.elements.llm",
                "class_name": "LLM"}}}]}
    path = tmp_path / "llm.json"
    path.write_text(json.dumps(definition))

    import queue
    responses = queue.Queue()
    pipeline = create_pipeline(str(path), runtime=runtime)
    stream = pipeline.create_stream_local("1", queue_response=responses)
    pipeline.create_frame_local(stream, {"text": "hi"})

    assert run_until(runtime, lambda: not responses.empty(), timeout=60.0)
    stream_id, frame_id, swag, metrics, okay, diagnostic = responses.get()
    assert okay, diagnostic
    assert isinstance(swag["text"], str)
    pipeline.stop()


def test_llm_element_max_slots_parameter(runtime, tmp_path):
    """``max_slots`` sizes the element's device batch; requests beyond
    it queue and still all complete."""
    definition = {
        "version": 0, "name": "llm_slots", "runtime": "jax",
        "graph": ["(llm)"],
        "elements": [{
            "name": "llm",
            "input": [{"name": "text"}],
            "output": [{"name": "text"}],
            "parameters": {"max_new_tokens": 4, "max_seq": 64,
                           "max_slots": 3},
            "deploy": {"local": {
                "module": "aiko_services_tpu.elements.llm",
                "class_name": "LLM"}}}]}
    path = tmp_path / "llm.json"
    path.write_text(json.dumps(definition))

    import queue
    responses = queue.Queue()
    pipeline = create_pipeline(str(path), runtime=runtime)
    stream = pipeline.create_stream_local("1", queue_response=responses)
    for i in range(5):                         # 5 requests, 3 slots
        pipeline.create_frame_local(stream, {"text": f"hi {i}"})
    assert run_until(runtime, lambda: responses.qsize() >= 5,
                     timeout=120.0)
    while not responses.empty():
        *_, okay, diagnostic = responses.get()
        assert okay, diagnostic
    assert pipeline.graph.get_node("llm").element._batcher.max_slots == 3
    pipeline.stop()
