"""Stage-parallel execution over placed submeshes (ISSUE 3): per-stage
admission windows, stage-worker overlap of synchronous placed stages,
in-order per-stream delivery, topology/profile-aware placement, memoized
async stage hops, remote-retry backoff, and replace() under
stage-parallel flight -- on the 8-device CPU mesh."""

import queue
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import run_until

from aiko_services_tpu.pipeline import Pipeline
from aiko_services_tpu.pipeline.stages import StageScheduler
from aiko_services_tpu.pipeline.tensor import StagePlacement, device_sort_key

COMMON = "aiko_services_tpu.elements.common"

import threading

from aiko_services_tpu.pipeline import PipelineElement, StreamEvent


class SlowAsync(PipelineElement):
    """Async element tracking its peak concurrent parked frames --
    loaded by module path ("tests/test_stages.py")."""

    is_async = True
    _lock = threading.Lock()
    inflight = 0
    peak = 0

    def process_frame(self, stream, x=None):
        return StreamEvent.OKAY, {"x": x}

    def process_frame_start(self, stream, complete, x=None):
        with SlowAsync._lock:
            SlowAsync.inflight += 1
            SlowAsync.peak = max(SlowAsync.peak, SlowAsync.inflight)

        def work():
            time.sleep(0.05)
            with SlowAsync._lock:
                SlowAsync.inflight -= 1
            complete(StreamEvent.OKAY, {"x": x})

        threading.Thread(target=work, daemon=True).start()


def element(name, cls, inputs, outputs, parameters=None, placement=None,
            module=COMMON):
    definition = {"name": name,
                  "input": [{"name": n} for n in inputs],
                  "output": [{"name": n} for n in outputs],
                  "deploy": {"local": {"module": module,
                                       "class_name": cls}},
                  "parameters": parameters or {}}
    if placement:
        definition["placement"] = placement
    return definition


def two_stage_definition(busy_a=20.0, busy_b=20.0, parameters=None,
                         devices_a=4, devices_b=4):
    return {
        "version": 0, "name": "p_stages", "runtime": "jax",
        "graph": ["(detect llm)"],
        "parameters": dict(parameters or {}),
        "elements": [
            element("detect", "StageWork", ["x"], ["x"],
                    {"busy_ms": busy_a, "factor": 2.0},
                    {"devices": devices_a}),
            element("llm", "StageWork", ["x"], ["x"],
                    {"busy_ms": busy_b, "factor": 3.0},
                    {"devices": devices_b}),
        ]}


def pump_and_drain(runtime, pipeline, n_frames, stream_id="s",
                   timeout=30.0):
    responses = queue.Queue()
    for i in range(n_frames):
        pipeline.process_frame_local(
            {"x": np.full((8, 8), float(i + 1), np.float32)},
            stream_id=stream_id, queue_response=responses)
    collected = []

    def drained():
        while not responses.empty():
            collected.append(responses.get())
        return len(collected) >= n_frames

    assert run_until(runtime, drained, timeout=timeout), \
        f"only {len(collected)}/{n_frames} frames completed"
    return collected


# -- the tentpole: cross-stage pipelining -----------------------------------

def test_two_stage_overlap_and_in_order_delivery(runtime):
    """Steady state: frame k+1's detect span starts BEFORE frame k's llm
    span ends (both stages concurrently busy), while responses arrive in
    ingest order."""
    pipeline = Pipeline(two_stage_definition(), runtime=runtime)
    assert pipeline.stage_scheduler is not None
    collected = pump_and_drain(runtime, pipeline, 6)

    frame_ids = [row[1] for row in collected]
    assert frame_ids == sorted(frame_ids), \
        f"delivery out of ingest order: {frame_ids}"
    for *_, okay, diagnostic in collected:
        assert okay, diagnostic
    spans = {}
    for _, frame_id, _swag, metrics, *_ in collected:
        spans[frame_id] = metrics
    overlaps = 0
    for k in range(len(spans) - 1):
        llm_end = spans[k]["llm_time_start"] + spans[k]["llm_time"]
        if spans[k + 1]["detect_time_start"] < llm_end:
            overlaps += 1
    assert overlaps >= 2, (
        f"no cross-stage overlap: detect(k+1) never started before "
        f"llm(k) ended ({overlaps} overlaps in {len(spans)} frames)")
    # Occupancy accounting saw both stages busy.
    stats = pipeline.stage_stats()
    assert stats["detect"]["admitted"] >= 6
    assert stats["llm"]["admitted"] >= 6
    assert stats["detect"]["occupancy"] > 0
    assert stats["llm"]["occupancy"] > 0
    pipeline.stop()


def test_stage_pipeline_throughput_vs_serial_walk(runtime):
    """The acceptance ratio: stage-parallel fps >= 1.5x the serial
    stage-walk baseline (``stage_pipeline: off``) on the synthetic
    two-stage workload -- throughput approaches the slower stage's solo
    rate instead of the sum of both stages."""
    frames = 12

    def run_mode(mode, name):
        definition = two_stage_definition(
            busy_a=25.0, busy_b=25.0,
            parameters={"stage_pipeline": mode})
        definition["name"] = name
        pipeline = Pipeline(definition, runtime=runtime)
        pump_and_drain(runtime, pipeline, 2, stream_id="warm")  # warm jit
        start = time.perf_counter()
        pump_and_drain(runtime, pipeline, frames, stream_id="timed")
        elapsed = time.perf_counter() - start
        pipeline.stop()
        return frames / elapsed

    serial_fps = run_mode("off", "p_serial")
    pipelined_fps = run_mode("auto", "p_pipelined")
    assert pipelined_fps >= 1.5 * serial_fps, (
        f"stage pipelining {pipelined_fps:.1f} fps vs serial "
        f"{serial_fps:.1f} fps: below the 1.5x acceptance ratio")


def test_stage_admission_window_bounds_inflight(runtime):
    """depth=1: at most one frame inside each stage at a time, queued
    frames counted, and everything still completes in order."""
    # llm deliberately slower than detect so frames always ARRIVE at a
    # still-busy llm window (a symmetric split would race the release).
    pipeline = Pipeline(two_stage_definition(
        busy_a=5.0, busy_b=20.0,
        parameters={"stage_inflight": 1}), runtime=runtime)
    assert pipeline.stage_scheduler.depth == 1
    collected = pump_and_drain(runtime, pipeline, 5)
    assert [row[1] for row in collected] == sorted(
        row[1] for row in collected)
    stats = pipeline.stage_stats()
    for stage in ("detect", "llm"):
        assert stats[stage]["active"] == 0          # all released
        assert stats[stage]["admitted"] >= 5
    assert stats["llm"]["queued"] >= 1, \
        "a full depth-1 window never queued a frame"
    pipeline.stop()


def test_single_placed_stage_has_no_scheduler(runtime):
    """One placed stage has nothing to overlap with: the per-element
    path (and immediate responses) stay exactly as before."""
    definition = {
        "version": 0, "name": "p_single", "runtime": "jax",
        "graph": ["(only)"],
        "elements": [element("only", "StageWork", ["x"], ["x"],
                             {"factor": 2.0}, {"devices": 4})]}
    pipeline = Pipeline(definition, runtime=runtime)
    assert pipeline.stage_scheduler is None
    collected = pump_and_drain(runtime, pipeline, 2)
    assert all(okay for *_, okay, _d in collected)
    pipeline.stop()


def test_stage_local_fused_segment_runs_on_stage_worker(runtime):
    """A fusable device chain AFTER a placed head fuses stage-locally
    (segment.stage_context = the head's stage) and dispatches on that
    stage's worker thread -- one fused dispatch per frame, results
    identical to per-element, delivery in order."""
    definition = {
        "version": 0, "name": "p_fused_stage", "runtime": "jax",
        "graph": ["(detect llm dbl inc)"],
        "parameters": {"transfer_guard": "disallow"},
        "elements": [
            element("detect", "StageWork", ["x"], ["x"],
                    {"busy_ms": 5.0, "factor": 2.0}, {"devices": 4}),
            element("llm", "StageWork", ["x"], ["x"],
                    {"busy_ms": 5.0, "factor": 3.0}, {"devices": 4}),
            element("dbl", "DeviceDouble", ["x"], ["x"],
                    module="tests/test_fusion.py"),
            element("inc", "DeviceAddOne", ["x"], ["x"],
                    module="tests/test_fusion.py"),
        ]}
    pipeline = Pipeline(definition, runtime=runtime)
    collected = pump_and_drain(runtime, pipeline, 4)
    assert [row[1] for row in collected] == [0, 1, 2, 3]
    for _, frame_id, swag, metrics, okay, diagnostic in collected:
        assert okay, diagnostic
        expected = (frame_id + 1) * 2.0 * 3.0 * 2.0 + 1.0
        np.testing.assert_allclose(np.asarray(swag["x"])[0, 0], expected)
        assert metrics.get("fused_segments") == 1
    assert len(pipeline.fused_segments) == 1
    segment = pipeline.fused_segments[0]
    assert segment.stage_context == "llm"
    assert segment.calls == 4
    assert not segment.broken
    # The segment dispatched on the llm stage's worker, not the loop.
    worker = pipeline.stage_scheduler.executor("llm")
    assert worker.executed >= 4
    pipeline.stop()


# -- topology- and profile-aware placement ----------------------------------

def test_devices_sorted_by_coords_with_id_fallback():
    class FakeDevice:
        def __init__(self, id, coords=None):
            self.id = id
            self.coords = coords

    a = FakeDevice(3, (1, 0, 0))
    b = FakeDevice(1, (0, 1, 0))
    c = FakeDevice(2, (0, 0, 0))
    placement = StagePlacement([a, b, c])
    assert placement.devices == [c, b, a]       # coords order, not id
    plain = StagePlacement([FakeDevice(2), FakeDevice(0), FakeDevice(1)])
    assert [d.id for d in plain.devices] == [0, 1, 2]
    # jax CPU devices sort by id (no coords) and stay stable.
    placement = StagePlacement(list(reversed(jax.devices())))
    assert [d.id for d in placement.devices] == list(range(8))


def test_auto_split_equal_until_profiled():
    placement = StagePlacement(jax.devices())
    plans = placement.assign({"a": "auto", "b": "auto"})
    assert {name: plan.mesh.devices.size
            for name, plan in plans.items()} == {"a": 4, "b": 4}


def test_auto_split_proportional_to_cost_and_rebalanced_on_replace():
    placement = StagePlacement(jax.devices())
    placement.assign({"a": "auto", "b": "auto"},
                     costs={"a": 0.010, "b": 0.030})
    sizes = {name: plan.mesh.devices.size
             for name, plan in placement.plans.items()}
    assert sizes == {"a": 2, "b": 6}
    # Two of b's chips die: the auto split re-balances over the 6
    # survivors with the same 1:3 profile.
    dead = list(placement.plans["b"].mesh.devices.flat)[:2]
    placement.replace(dead)
    sizes = {name: plan.mesh.devices.size
             for name, plan in placement.plans.items()}
    assert sum(sizes.values()) == 6
    assert sizes["b"] > sizes["a"]
    assert placement.generation == 1


def test_auto_split_with_fixed_stage():
    placement = StagePlacement(jax.devices())
    plans = placement.assign({"fixed": {"tp": 2}, "x": "auto",
                              "y": "auto"})
    assert plans["fixed"].mesh.shape["tp"] == 2
    assert plans["x"].mesh.devices.size + plans["y"].mesh.devices.size \
        == 6


def test_auto_split_overflow_rejected():
    placement = StagePlacement(jax.devices())
    with pytest.raises(ValueError, match="want"):
        placement.assign({"fixed": 8, "auto_stage": "auto"})


# -- memoized, resident-skipping stage hops ---------------------------------

def test_transfer_memoizes_shardings_and_skips_resident_leaves():
    placement = StagePlacement(jax.devices())
    placement.assign({"a": {"dp": 4}, "b": {"dp": 4}})
    x = jnp.ones((8, 8))
    on_b = placement.transfer(x, "b")
    puts = placement.transfer_puts
    cached = len(placement._shardings)
    assert cached == 1
    # Same stage again: sharding memo hit, and the already-resident
    # leaf passes through untouched (no device_put walk).
    again = placement.transfer(on_b, "b")
    assert again is not None
    assert placement.transfer_puts == puts          # nothing moved
    assert placement.transfer_skipped >= 1
    assert len(placement._shardings) == cached
    # Hopping to the OTHER stage is a real move.
    on_a = placement.transfer(on_b, "a")
    assert placement.transfer_puts == puts + 1
    np.testing.assert_array_equal(np.asarray(on_a), np.asarray(x))


def test_transfer_sharding_cache_invalidated_by_replace():
    placement = StagePlacement(jax.devices())
    placement.assign({"a": {"dp": 4}, "b": {"dp": 4}})
    before = placement.transfer(jnp.ones((4, 4)), "b")
    placement.replace(list(placement.plans["a"].mesh.devices.flat)[:2])
    after = placement.transfer(before, "b")
    survivors = set(placement.devices)
    assert set(after.sharding.device_set) <= survivors


# -- remote-stage retry backoff ---------------------------------------------

def test_remote_retry_exponential_backoff(runtime):
    from aiko_services_tpu.services import Registrar

    Registrar(runtime=runtime, primary_search_timeout=0.05)
    front = Pipeline(
        {"version": 0, "name": "front_backoff", "runtime": "jax",
         "graph": ["(inc fwd)"],
         "elements": [
             element("inc", "Increment", ["x"], ["x"]),
             {"name": "fwd", "input": [{"name": "x"}],
              "output": [{"name": "x"}],
              "deploy": {"remote": {"name": "never_appears"}}}]},
        runtime=runtime)
    responses = queue.Queue()
    front.create_stream_local("1", queue_response=responses)
    front.ingest_local("1", {"x": 0}, queue_response=responses)
    runtime.run(timeout=1.8)
    frame = front.streams["1"].frames[0]
    # Fixed 0.25 s retries would have fired ~7 times by 1.8 s; backoff
    # (0.25, 0.5, 1.0, ...) fires at most 4 -- and the count is VISIBLE
    # on the share dict, not a silent hot loop.
    assert 1 <= frame.remote_retries <= 4, frame.remote_retries
    assert front.share["remote_stage_retries"] == frame.remote_retries
    assert frame.metrics["remote_retries"] == frame.remote_retries
    assert front.streams["1"].in_flight == 1        # still parked
    front.stop()


# -- replace() under stage-parallel flight ----------------------------------

def test_replace_under_stage_parallel_execution(runtime):
    """Chips die between bursts of a stage-parallel stream: in-flight
    frames complete (or error) cleanly, and frames after the
    replacement run on the NEW generation's submeshes -- never against
    a stale mesh."""
    pipeline = Pipeline(two_stage_definition(busy_a=5.0, busy_b=5.0),
                        runtime=runtime)
    placement = pipeline.stage_placement
    collected = pump_and_drain(runtime, pipeline, 4)
    assert all(okay for *_, okay, _d in collected)
    assert placement.generation == 0

    detect_devices = list(placement.plans["detect"].mesh.devices.flat)
    dead = set(detect_devices[:2])
    failed = pipeline.check_device_health(prober=lambda d: d not in dead)
    assert set(failed) == dead
    assert placement.generation == 1

    collected = pump_and_drain(runtime, pipeline, 4, stream_id="s2")
    for *_, okay, diagnostic in collected:
        assert okay, diagnostic
    survivors = set(placement.devices)
    assert not survivors & dead
    for _, _fid, swag, metrics, *_ in collected:
        leaf = swag["x"]
        assert set(leaf.sharding.device_set) <= survivors, \
            "frame ran against a stale (pre-replacement) mesh"
    # The new generation's hops filled a fresh sharding cache
    # (key = (stage, replica, generation, spec)).
    assert all(key[2] == 1 for key in placement._shardings)
    pipeline.stop()


def test_replace_midflight_frames_never_use_stale_mesh(runtime):
    """Frames IN FLIGHT across the replacement: every output that
    completes after the swap is resident on surviving devices only."""
    pipeline = Pipeline(two_stage_definition(busy_a=15.0, busy_b=15.0),
                        runtime=runtime)
    placement = pipeline.stage_placement
    responses = queue.Queue()
    for i in range(6):
        pipeline.process_frame_local(
            {"x": np.full((8, 8), float(i + 1), np.float32)},
            stream_id="mid", queue_response=responses)
    detect_devices = list(placement.plans["detect"].mesh.devices.flat)
    dead = set(detect_devices[:2])

    # Inject the failure while frames are mid-pipeline: run the loop
    # briefly, then health-check from the loop via the actor mailbox.
    runtime.run(timeout=0.03)
    pipeline.check_device_health(prober=lambda d: d not in dead)
    collected = []

    def drained():
        while not responses.empty():
            collected.append(responses.get())
        return len(collected) >= 6

    run_until(runtime, drained, timeout=30.0)
    survivors = set(placement.devices)
    new_generation = 0
    for _, _fid, swag, metrics, okay, diagnostic in collected:
        if not okay:
            continue        # erroring cleanly at the swap is legal
        leaf = swag.get("x")
        if metrics.get("stage_llm_generation") == 1:
            # Admitted to llm AFTER the swap: must be on the new
            # submeshes, never the stale mesh.
            new_generation += 1
            assert hasattr(leaf, "sharding")
            assert set(leaf.sharding.device_set) <= survivors, \
                "post-replacement frame ran against a stale mesh"
    assert new_generation >= 1, \
        "no frame demonstrably re-entered at the new generation"
    pipeline.stop()


# -- failure paths must not wedge the (pipeline-global) window ---------------

def test_frame_error_releases_credits_for_other_streams(runtime):
    """A poison frame errors its stream while other frames are parked
    on stage workers / queued for admission: every stage credit comes
    back, and a FRESH stream still flows (leaked credits would wedge
    every stream at the stage)."""
    pipeline = Pipeline(two_stage_definition(busy_a=10.0, busy_b=10.0),
                        runtime=runtime)
    responses = queue.Queue()
    for i in range(3):
        pipeline.process_frame_local(
            {"x": np.full((4, 4), float(i + 1), np.float32)},
            stream_id="s1", queue_response=responses)
    # Poison: StageWork's jitted multiply raises on None input (on the
    # stage worker), erroring the stream with frames still in flight.
    pipeline.process_frame_local({"x": None}, stream_id="s1",
                                 queue_response=responses)
    for i in range(2):
        pipeline.process_frame_local(
            {"x": np.full((4, 4), 1.0, np.float32)},
            stream_id="s1", queue_response=responses)
    collected = []

    def saw_error():
        while not responses.empty():
            collected.append(responses.get())
        return any(not row[4] for row in collected)

    assert run_until(runtime, saw_error, timeout=30.0)
    runtime.run(timeout=0.3)            # let teardown posts drain
    stats = pipeline.stage_stats()
    for stage in ("detect", "llm"):
        assert stats[stage]["active"] == 0, \
            f"stage {stage} leaked admission credits: {stats[stage]}"
        assert pipeline.stage_scheduler.waiting(stage) == 0
    # The window still admits: a new stream completes all its frames.
    fresh = pump_and_drain(runtime, pipeline, 4, stream_id="s2")
    for *_, okay, diagnostic in fresh:
        assert okay, diagnostic
    pipeline.stop()


def test_error_flushes_buffered_successor_responses(runtime):
    """A frame error must not drop the buffered okay-responses of
    successors that already completed out of order: the error delivers
    in its slot and the finished work flushes behind it."""
    from aiko_services_tpu.pipeline.stream import Frame

    pipeline = Pipeline(two_stage_definition(), runtime=runtime)
    responses = queue.Queue()
    stream = pipeline.create_stream_local("w", queue_response=responses)
    f0, f1 = Frame(frame_id=0), Frame(frame_id=1)
    pipeline._assign_delivery_seq(stream, f0)
    pipeline._assign_delivery_seq(stream, f1)
    stream.frames[0] = f0
    # Frame 1 completes FIRST: its response buffers behind frame 0.
    pipeline._deliver(stream, f1, okay=True)
    assert responses.empty()
    pipeline._frame_error(stream, f0, "boom")
    got = [responses.get_nowait() for _ in range(2)]
    assert [row[1] for row in got] == [0, 1]        # seq order kept
    assert got[0][4] is False and "boom" in got[0][5]
    assert got[1][4] is True, "successor's completed response was lost"
    pipeline.stop()


def test_bad_devices_request_is_definition_error(runtime):
    from aiko_services_tpu.pipeline.definition import DefinitionError

    definition = two_stage_definition()
    definition["elements"][0]["placement"] = {"devices": "atuo"}  # typo
    with pytest.raises(DefinitionError, match="devices"):
        Pipeline(definition, runtime=runtime)


def test_stream_recreated_with_same_id_runs_full_path(runtime):
    """Destroy a stream mid-flight (queued waiters, parked workers),
    recreate it under the SAME id: every new frame walks the FULL path
    (stale waiter tokens must never admit a new frame mid-pipeline)."""
    pipeline = Pipeline(two_stage_definition(
        busy_a=5.0, busy_b=30.0,
        parameters={"stage_inflight": 1}), runtime=runtime)
    limbo = queue.Queue()
    for i in range(3):
        pipeline.process_frame_local(
            {"x": np.full((4, 4), 1.0, np.float32)},
            stream_id="r", queue_response=limbo)
    runtime.run(timeout=0.05)           # frames spread across stages
    pipeline.destroy_stream("r")
    collected = pump_and_drain(runtime, pipeline, 3, stream_id="r",
                               timeout=30.0)
    for _, frame_id, swag, _metrics, okay, diagnostic in collected:
        assert okay, diagnostic
        # detect (x2) AND llm (x3) both ran exactly once:
        # (frame_id + 1) * 2 * 3.
        np.testing.assert_allclose(np.asarray(swag["x"])[0, 0],
                                   (frame_id + 1) * 6.0)
    pipeline.stop()


# -- scheduler unit behaviour ------------------------------------------------

def test_scheduler_credits_and_waiters():
    scheduler = StageScheduler(["a", "b"], depth=2)
    assert scheduler.try_admit("a")
    assert scheduler.try_admit("a")
    assert not scheduler.try_admit("a")             # window full
    scheduler.enqueue("a", ("s", 1, "a"))
    token = scheduler.release("a")
    assert token == ("s", 1, "a")                   # freed credit -> waiter
    assert scheduler.active("a") == 1
    assert scheduler.stats["a"]["queued"] == 1
    scheduler.stop()


def test_in_stage_async_park_releases_stage_credit(runtime):
    """An async element DEEPER in a stage (not the placed head) still
    releases the stage credit at its park: cross-frame batching at the
    async element must not be capped at the admission window depth."""
    SlowAsync.inflight = 0
    SlowAsync.peak = 0
    definition = two_stage_definition(busy_a=1.0, busy_b=1.0)
    definition["graph"] = ["(detect batcher llm)"]
    definition["elements"].insert(1, element(
        "batcher", "SlowAsync", ["x"], ["x"],
        module="tests/test_stages.py"))
    pipeline = Pipeline(definition, runtime=runtime)
    # The element class is re-imported by module path: reach the live
    # class through the graph, not the pytest import of this file.
    live_cls = type(pipeline.graph.get_node("batcher").element)
    live_cls.inflight = 0
    live_cls.peak = 0
    collected = pump_and_drain(runtime, pipeline, 6)
    assert all(row[4] for row in collected)
    assert live_cls.peak > pipeline.stage_scheduler.depth, (
        f"peak {live_cls.peak} parked frames: detect credits were "
        f"held through the in-stage async park")
    pipeline.stop()


def test_scheduler_reservation_blocks_queue_jumping():
    """A popped waiter's freed credit is RESERVED until its admission
    post lands: a fresh admission attempt arriving in between must not
    steal it (a later frame would overtake an earlier one through a
    stateful stage)."""
    scheduler = StageScheduler(["a"], depth=1)
    assert scheduler.try_admit("a")
    scheduler.enqueue("a", ("s", 0, "a"))
    token = scheduler.release("a")          # pops + reserves
    assert token == ("s", 0, "a")
    assert not scheduler.try_admit("a"), \
        "fresh admission stole a popped waiter's reserved credit"
    assert scheduler.try_admit("a", reserved=True)
    assert scheduler.active("a") == 1
    # A dead popped token cancels its reservation instead of pinning it.
    scheduler.enqueue("a", ("s", 1, "a"))
    token = scheduler.release("a")
    scheduler.cancel_reservation("a")
    assert scheduler.try_admit("a")         # credit usable again
    scheduler.stop()


def test_scheduler_fresh_admission_uses_surplus_beyond_reservations():
    """A reservation pins exactly ONE credit: fresh admissions may
    still take genuinely free capacity beyond active + reserved."""
    scheduler = StageScheduler(["a"], depth=2)
    assert scheduler.try_admit("a")
    scheduler.enqueue("a", ("s", 0, "a"))
    token = scheduler.release("a")          # active 0, reserved 1
    assert token == ("s", 0, "a")
    assert scheduler.try_admit("a"), \
        "one reservation blocked the window's free surplus credit"
    assert not scheduler.try_admit("a")     # active 1 + reserved 1 = depth
    assert scheduler.try_admit("a", reserved=True)
    scheduler.stop()


def test_remote_park_releases_stage_credit(runtime):
    """Frames parked at (or retrying discovery of) a remote stage
    DOWNSTREAM of placed stages must not pin the placed stages'
    admission windows: later frames keep flowing through the submeshes
    while earlier ones wait on the fabric."""
    from aiko_services_tpu.services import Registrar

    Registrar(runtime=runtime, primary_search_timeout=0.05)
    definition = two_stage_definition(busy_a=2.0, busy_b=2.0)
    definition["graph"] = ["(detect llm fwd)"]
    definition["elements"].append(
        {"name": "fwd", "input": [{"name": "x"}],
         "output": [{"name": "x"}],
         "deploy": {"remote": {"name": "never_appears"}}})
    pipeline = Pipeline(definition, runtime=runtime)
    responses = queue.Queue()
    n_frames = 5                    # > 2x the default window depth
    for i in range(n_frames):
        pipeline.process_frame_local(
            {"x": np.full((4, 4), 1.0, np.float32)},
            stream_id="rp", queue_response=responses)
    runtime.run(timeout=1.0)
    stats = pipeline.stage_stats()
    # Every frame cleared BOTH placed stages (parked/retrying at the
    # remote now): with credits pinned across the remote park, only
    # stage_inflight frames could ever have entered llm.
    assert stats["llm"]["admitted"] == n_frames, stats
    assert stats["llm"]["active"] == 0, \
        f"remote park pinned llm admission credits: {stats['llm']}"
    assert stats["detect"]["active"] == 0
    assert pipeline.streams["rp"].in_flight == n_frames   # all parked
    pipeline.stop()


def test_scheduler_occupancy_window():
    scheduler = StageScheduler(["a"], depth=1)
    scheduler.try_admit("a")
    time.sleep(0.03)
    scheduler.release("a")
    assert scheduler.occupancy("a") > 0
    scheduler.reset_window()
    time.sleep(0.01)
    assert scheduler.occupancy("a") < 0.5           # idle since reset
    scheduler.stop()


# -- replicated stages (ISSUE 7) ---------------------------------------------


def replicated_definition(replicas=3, busy_ms=15.0, parameters=None,
                          devices=2):
    return {
        "version": 0, "name": "p_replicas", "runtime": "jax",
        "graph": ["(detect)"],
        "parameters": dict(parameters or {}),
        "elements": [
            element("detect", "StageWork", ["x"], ["x"],
                    {"busy_ms": busy_ms, "factor": 2.0},
                    {"devices": devices, "replicas": replicas}),
        ]}


def pump(pipeline, count, stream_id="r", shape=(8, 8)):
    responses = queue.Queue()
    rng = np.random.default_rng(0)
    for _ in range(count):
        pipeline.process_frame_local(
            {"x": rng.standard_normal(shape).astype(np.float32)},
            stream_id=stream_id, queue_response=responses)
    return responses


def drain(rt, responses, count, timeout=120.0):
    rows = []

    def drained():
        while not responses.empty():
            rows.append(responses.get())
        return len(rows) >= count

    run_until(rt, drained, timeout=timeout)
    return rows


def test_replica_group_round_robin_and_depth():
    from aiko_services_tpu.pipeline.stages import ReplicaGroup

    group = ReplicaGroup("detect", 3, depth=1)
    picks = []
    for _ in range(3):
        index = group.pick()
        picks.append(index)
        group.admit(index)
    assert picks == [0, 1, 2]
    assert group.pick() is None                 # window full everywhere
    group.release(1)
    assert group.pick() == 1                    # freed credit wins
    assert group.stats["live"] == 3


def test_replica_group_canary_lifecycle():
    from aiko_services_tpu.pipeline.stages import (
        REPLICA_DEAD, REPLICA_HALF_OPEN, REPLICA_LIVE, ReplicaGroup)

    group = ReplicaGroup("detect", 2, depth=2)
    group.fail(1)
    assert group.states == [REPLICA_LIVE, REPLICA_DEAD]
    assert group.failovers == 1
    group.rebuild(2, half_open=[1])
    assert group.states == [REPLICA_LIVE, REPLICA_HALF_OPEN]
    # The half-open slot admits exactly ONE canary.
    picks = [group.pick() for _ in range(3)]
    for index in picks:
        if index is not None:
            group.admit(index)
    assert picks.count(1) == 1
    # Canary success closes the slot live.
    group.release(1, ok=True)
    assert group.states[1] == REPLICA_LIVE
    # A second failure + rebuild, canary FAILURE re-kills.
    group.fail(1)
    group.rebuild(2, half_open=[1])
    index = None
    while index != 1:
        index = group.pick()
        group.admit(index)
    group.release(1, ok=False)
    assert group.states[1] == REPLICA_DEAD


def test_replica_group_all_dead():
    from aiko_services_tpu.pipeline.stages import ReplicaGroup

    group = ReplicaGroup("detect", 2)
    group.fail(0)
    assert not group.all_dead()
    group.fail(1)
    assert group.all_dead()
    assert group.pick() is None


def test_scheduler_admit_replica_respects_reservations():
    scheduler = StageScheduler(["detect"], depth=1,
                               replicas={"detect": 2})
    assert scheduler.admit_replica("detect") == 0
    assert scheduler.admit_replica("detect") == 1
    assert scheduler.admit_replica("detect") is None
    scheduler.enqueue("detect", ["s", 0, "detect", True, None])
    waiter = scheduler.release("detect", replica=0)
    assert waiter is not None                   # popped with reservation
    # A fresh admission may not steal the reserved credit...
    assert scheduler.admit_replica("detect") is None
    # ...but the reserved waiter itself admits.
    assert scheduler.admit_replica("detect", reserved=True) is not None
    scheduler.stop()


def test_replicated_stage_round_robins_frames(runtime):
    pipeline = Pipeline(replicated_definition(replicas=3, busy_ms=10.0),
                        runtime=runtime)
    group = pipeline.stage_scheduler.groups["detect"]
    rows = drain(runtime, pump(pipeline, 12), 12)
    assert len(rows) == 12
    assert all(row[4] for row in rows), \
        [row[5] for row in rows if not row[4]]
    order = [row[1] for row in rows]
    assert order == sorted(order)
    # Admission spread across every replica, and the per-frame metric
    # recorded which submesh each frame ran on.
    assert all(count >= 2 for count in group.admitted), group.admitted
    used = {row[3].get("stage_detect_replica") for row in rows}
    assert used == {0, 1, 2}
    stats = pipeline.replica_stats()
    assert stats["stages"]["detect"]["live"] == 3
    pipeline.stop()


def test_single_replicated_stage_activates_scheduler(runtime):
    """One placed stage normally runs the serial path, but replication
    IS frame-level parallelism -- the scheduler must activate."""
    pipeline = Pipeline(replicated_definition(replicas=2),
                        runtime=runtime)
    assert pipeline.stage_scheduler is not None
    assert "detect" in pipeline.stage_scheduler.groups
    pipeline.stop()


def test_replica_failover_sheds_to_peers_in_order(runtime):
    """Kill one replica of 3 mid-flight: its frames replay on the
    peers, every frame completes IN ORDER, no duplicates, and the
    stage keeps serving at N-1 -- the peer-shed path, generation
    unchanged."""
    pipeline = Pipeline(
        replicated_definition(replicas=3, busy_ms=20.0,
                              parameters={"replica_rebuild_ms": 0}),
        runtime=runtime)
    placement = pipeline.stage_placement
    responses = pump(pipeline, 12)
    pipeline.post_self("fail_replica", ["detect", 1], delay=0.05)
    rows = drain(runtime, responses, 12)
    assert len(rows) == 12, "stream hung after replica failover"
    assert all(row[4] for row in rows), \
        [row[5] for row in rows if not row[4]]
    order = [row[1] for row in rows]
    assert order == sorted(order), f"out of order: {order}"
    assert len(order) == len(set(order)), "duplicate delivery"
    # Peer-shed, not stop-the-world: no generation bump, peers alive.
    assert placement.generation == 0
    assert placement.live_replicas("detect") == [0, 2]
    stats = pipeline.replica_stats()
    assert stats["failovers"] == 1
    assert stats["failover_ms"] > 0
    assert pipeline.share["replica_failovers"] == 1
    pipeline.stop()


def test_replica_rebuild_readmits_half_open_behind_canary(runtime):
    """After a failover the background rebuild restores the slot
    HALF-OPEN: exactly one canary frame re-admits it, success closes
    it live and it serves again."""
    pipeline = Pipeline(
        replicated_definition(replicas=3, busy_ms=10.0,
                              parameters={"replica_rebuild_ms": 40}),
        runtime=runtime)
    group = pipeline.stage_scheduler.groups["detect"]
    responses = pump(pipeline, 8)
    pipeline.post_self("fail_replica", ["detect", 2], delay=0.03)
    rows = drain(runtime, responses, 8)
    assert all(row[4] for row in rows)
    run_until(runtime,
              lambda: pipeline.replica_stats()["rebuilds"] >= 1,
              timeout=30.0)
    walk = [(slot, state) for slot, state, _ in group.transitions]
    assert (2, "dead") in walk
    assert (2, "half_open") in walk
    # More traffic: the canary closes the slot live and it serves.
    rows2 = drain(runtime, pump(pipeline, 9, stream_id="r2"), 9)
    assert all(row[4] for row in rows2)
    assert group.states == ["live", "live", "live"]
    used = {row[3].get("stage_detect_replica") for row in rows2}
    assert 2 in used, "rebuilt replica never served"
    pipeline.stop()


def test_replica_canary_off_readmits_fully(runtime):
    pipeline = Pipeline(
        replicated_definition(
            replicas=2, busy_ms=5.0,
            parameters={"replica_rebuild_ms": 30,
                        "replica_canary": "off"}),
        runtime=runtime)
    group = pipeline.stage_scheduler.groups["detect"]
    rows = drain(runtime, pump(pipeline, 4), 4)
    assert all(row[4] for row in rows)
    pipeline.post_self("fail_replica", ["detect", 0])
    run_until(runtime,
              lambda: pipeline.replica_stats()["rebuilds"] >= 1,
              timeout=30.0)
    walk = [(slot, state) for slot, state, _ in group.transitions]
    assert (0, "half_open") not in walk
    assert group.states == ["live", "live"]
    pipeline.stop()


def test_replica_failover_resets_remote_retry_backoff(runtime):
    """A frame punished for a dead replica's failures starts clean on
    a healthy peer: ``remote_retries`` (the exponential-backoff state)
    resets when the failover re-admits it elsewhere."""
    pipeline = Pipeline(
        replicated_definition(replicas=2, busy_ms=60.0,
                              parameters={"replica_rebuild_ms": 0}),
        runtime=runtime)
    responses = pump(pipeline, 4)
    # Let frames admit onto stage workers.
    run_until(runtime,
              lambda: any(frame.stage == "detect"
                          for stream in pipeline.streams.values()
                          for frame in stream.frames.values()),
              timeout=30.0)
    victims = [frame for stream in pipeline.streams.values()
               for frame in stream.frames.values()
               if frame.stage == "detect" and frame.stage_replica == 0]
    assert victims, "no frame admitted to replica 0"
    for frame in victims:
        frame.remote_retries = 3        # poisoned backoff state
    pipeline.fail_replica("detect", 0)
    for frame in victims:
        assert frame.remote_retries == 0
    rows = drain(runtime, responses, 4)
    assert all(row[4] for row in rows)
    pipeline.stop()


def test_all_replicas_dead_fails_frames_then_rebuild_recovers(runtime):
    """Every replica dead and no rebuild pending: incoming frames fail
    fast (stream stays alive) instead of queueing forever."""
    pipeline = Pipeline(
        replicated_definition(replicas=2, busy_ms=5.0,
                              parameters={"replica_rebuild_ms": 0}),
        runtime=runtime)
    rows = drain(runtime, pump(pipeline, 2), 2)
    assert all(row[4] for row in rows)
    pipeline.fail_replica("detect", 0)
    # The LAST replica's failure escalates to an immediate rebuild --
    # the stage cannot serve at N-0 -- which restores both slots.
    pipeline.fail_replica("detect", 1)
    assert pipeline.replica_stats()["rebuilds"] == 1
    rows2 = drain(runtime, pump(pipeline, 4, stream_id="r2"), 4)
    assert all(row[4] for row in rows2)
    pipeline.stop()


def test_autoscale_scales_up_on_queue_and_down_on_idle(runtime):
    pipeline = Pipeline(
        {"version": 0, "name": "p_autoscale", "runtime": "jax",
         "graph": ["(detect)"],
         "elements": [
             element("detect", "StageWork", ["x"], ["x"],
                     {"busy_ms": 5.0, "factor": 2.0},
                     {"devices": 1,
                      "replicas": {"min": 1, "max": 3}})]},
        runtime=runtime)
    placement = pipeline.stage_placement
    scheduler = pipeline.stage_scheduler
    group = scheduler.groups["detect"]
    assert placement.replica_total("detect") == 1   # starts at min
    # Synthesize load: the one replica ran hot all window and a frame
    # is queued behind it.
    group._busy[0] = 10.0
    group._window_start = time.monotonic() - 10.0
    scheduler.enqueue("detect", ["s", 0, "detect", True, None])
    decisions = pipeline.autoscale_replicas()
    assert decisions == {"detect": 2}
    assert placement.replica_total("detect") == 2
    scheduler._waiters["detect"].clear()
    scheduler.queued["detect"] = 0
    # Idle window: scale back down toward min.
    group = scheduler.groups["detect"]
    decisions = pipeline.autoscale_replicas()
    assert decisions == {"detect": 1}
    assert placement.replica_total("detect") == 1
    # At the floor with no load: no decision.
    assert pipeline.autoscale_replicas() == {}
    pipeline.stop()


def test_autoscaled_pipeline_serves_through_resplit(runtime):
    """Frames in flight when the autoscaler re-splits replicas replay
    onto the fresh carve and deliver in order."""
    pipeline = Pipeline(
        {"version": 0, "name": "p_autoscale2", "runtime": "jax",
         "graph": ["(detect)"],
         "elements": [
             element("detect", "StageWork", ["x"], ["x"],
                     {"busy_ms": 15.0, "factor": 2.0},
                     {"devices": 1,
                      "replicas": {"min": 1, "max": 4}})]},
        runtime=runtime)
    scheduler = pipeline.stage_scheduler
    group = scheduler.groups["detect"]
    responses = pump(pipeline, 10)

    fired = []

    def resplit():
        if not fired:
            group._busy[0] = 10.0
            group._window_start = time.monotonic() - 10.0
            fired.append(pipeline.autoscale_replicas())

    pipeline.post_self("autoscale_replicas", [], delay=0.04)
    rows = drain(runtime, responses, 10)
    assert len(rows) == 10
    assert all(row[4] for row in rows), \
        [row[5] for row in rows if not row[4]]
    order = [row[1] for row in rows]
    assert order == sorted(order)
    pipeline.stop()


def test_administrative_resplit_does_not_charge_replay_budget(runtime):
    """Consecutive autoscale re-splits under a sustained backlog must
    not exhaust ``replay_limit``: the engine's own re-carve is not a
    failure, so frames replayed by it keep their full recovery budget
    (regression: with replay_limit 1, two re-splits used to error the
    whole backlog)."""
    pipeline = Pipeline(
        {"version": 0, "name": "p_resplit_budget", "runtime": "jax",
         "graph": ["(detect)"],
         "parameters": {"replay_limit": 1},
         "elements": [
             element("detect", "StageWork", ["x"], ["x"],
                     {"busy_ms": 20.0, "factor": 2.0},
                     {"devices": 1,
                      "replicas": {"min": 1, "max": 4}})]},
        runtime=runtime)
    placement = pipeline.stage_placement
    scheduler = pipeline.stage_scheduler
    responses = pump(pipeline, 10)
    run_until(runtime,
              lambda: any(frame.stage == "detect"
                          for stream in pipeline.streams.values()
                          for frame in stream.frames.values()),
              timeout=30.0)
    for _ in range(2):                      # two consecutive up-ticks
        group = scheduler.groups["detect"]
        group._busy = [10.0] * len(group.states)
        group._window_start = time.monotonic() - 10.0
        scheduler.enqueue("detect", ["s", 99, "detect", True, None])
        assert pipeline.autoscale_replicas(), "no scale-up decision"
        scheduler._waiters["detect"].clear()
        scheduler.queued["detect"] = 0
    assert placement.replica_total("detect") == 3
    rows = drain(runtime, responses, 10)
    assert all(row[4] for row in rows), \
        [row[5] for row in rows if not row[4]]
    order = [row[1] for row in rows]
    assert order == sorted(order)
    # The budget is intact: no frame consumed a failure replay.
    for stream in pipeline.streams.values():
        for frame in stream.frames.values():
            assert frame.replays == 0
    pipeline.stop()


def test_replicated_stage_with_stage_pipeline_off_recovers(runtime):
    """``stage_pipeline: off`` disables replica admission, but a dead
    replica's chips are still dead -- fail_replica must escalate to the
    full replace path instead of silently leaving a dead submesh in the
    pool (regression: it used to no-op without a scheduler)."""
    pipeline = Pipeline(
        replicated_definition(replicas=2, busy_ms=5.0,
                              parameters={"stage_pipeline": "off"}),
        runtime=runtime)
    assert pipeline.stage_scheduler is None
    placement = pipeline.stage_placement
    doomed = placement.replica_devices("detect", 0)
    rows = drain(runtime, pump(pipeline, 2), 2)
    assert all(row[4] for row in rows)
    pipeline.fail_replica("detect", 0)
    assert placement.generation == 1, "dead replica never recovered"
    assert not (set(placement.devices) & doomed), \
        "dead chips still in the pool"
    rows2 = drain(runtime, pump(pipeline, 3, stream_id="r2"), 3)
    assert all(row[4] for row in rows2)
    pipeline.stop()


def test_autoscale_skips_scale_up_without_free_capacity(runtime):
    """A full pool cannot host another fixed-request replica: the
    control loop must not emit the decision at all -- the reassign
    would shed the increment straight back while still replaying every
    in-flight frame, every tick (regression)."""
    import jax
    n = len(jax.devices())
    pipeline = Pipeline(
        {"version": 0, "name": "p_full_pool", "runtime": "jax",
         "graph": ["(detect)"],
         "elements": [
             element("detect", "StageWork", ["x"], ["x"],
                     {"busy_ms": 5.0, "factor": 2.0},
                     {"devices": 1,
                      "replicas": {"min": n, "max": n + 4}})]},
        runtime=runtime)
    placement = pipeline.stage_placement
    scheduler = pipeline.stage_scheduler
    group = scheduler.groups["detect"]
    assert placement.replica_total("detect") == n    # pool exhausted
    generation = placement.generation
    # Hot + queued: the up-condition holds, but there is no capacity.
    group._busy = [10.0] * len(group.states)
    group._window_start = time.monotonic() - 10.0
    scheduler.enqueue("detect", ["s", 0, "detect", True, None])
    assert pipeline.autoscale_replicas() == {}
    assert placement.generation == generation, \
        "no-op scale-up still re-carved the placement"
    pipeline.stop()
