"""Orchestration layer: ProcessManager child reaping; LifeCycleManager /
LifeCycleClient handshake, EC state watch, deletion, and crash detection —
all in one process over the loopback broker (SURVEY §4 philosophy)."""

import sys

from conftest import run_until

from aiko_services_tpu.orchestration import (
    ProcessManager, LifeCycleManager, LifeCycleClient)
from aiko_services_tpu.services import Registrar


def test_process_manager_spawn_and_reap(runtime):
    exits = []
    manager = ProcessManager(
        engine=runtime.engine, poll_period=0.05,
        exit_handler=lambda id, p, rc: exits.append((id, rc)))
    manager.spawn("quick", sys.executable, ["-c", "import sys; sys.exit(3)"])
    assert run_until(runtime, lambda: exits == [("quick", 3)], timeout=10.0)
    assert len(manager) == 0
    manager.terminate()


def test_process_manager_destroy(runtime):
    exits = []
    manager = ProcessManager(
        engine=runtime.engine, poll_period=0.05,
        exit_handler=lambda id, p, rc: exits.append(id))
    manager.spawn("sleeper", sys.executable,
                  ["-c", "import time; time.sleep(60)"])
    manager.destroy("sleeper")
    assert run_until(runtime, lambda: exits == ["sleeper"], timeout=10.0)
    manager.terminate()


def _fleet(runtime, **kwargs):
    """Manager whose launcher instantiates clients in-process."""
    clients = {}

    def launcher(client_id, manager_topic):
        clients[client_id] = LifeCycleClient(
            f"worker_{client_id}", client_id, manager_topic, runtime=runtime)

    manager = LifeCycleManager(launcher=launcher, runtime=runtime, **kwargs)
    return manager, clients


def test_lifecycle_handshake_and_state_watch(runtime):
    Registrar(runtime=runtime, primary_search_timeout=0.05)
    manager, clients = _fleet(runtime)

    ids = [manager.create_client() for _ in range(3)]
    assert run_until(runtime, lambda: manager.client_count() == 3,
                     timeout=5.0)
    assert sorted(manager.clients) == sorted(ids)
    assert manager.share["client_count"] == 3

    # The per-client ECConsumer mirrors the worker's lifecycle state.
    assert run_until(
        runtime,
        lambda: all(rec.ec_cache.get("lifecycle") == "ready"
                    for rec in manager.clients.values()),
        timeout=5.0)
    manager.stop()


def test_lifecycle_destroy_client(runtime):
    from aiko_services_tpu.services.share import services_cache_singleton

    Registrar(runtime=runtime, primary_search_timeout=0.05)
    events = []
    manager, clients = _fleet(
        runtime, client_change_handler=lambda ev, cid: events.append((ev,
                                                                      cid)))
    cid = manager.create_client()
    assert run_until(runtime, lambda: manager.client_count() == 1,
                     timeout=5.0)
    # Deletion detection rides the Registrar event stream: wait until the
    # directory has actually seen the worker before destroying it.
    cache = services_cache_singleton(runtime)
    worker_topic = manager.clients[cid].topic_path
    assert run_until(runtime,
                     lambda: cache.registry.get(worker_topic) is not None,
                     timeout=5.0)

    manager.destroy_client(cid)
    # Client honors (terminate): deregisters; registrar remove event drops
    # it from the manager's fleet.
    assert run_until(runtime, lambda: manager.client_count() == 0,
                     timeout=5.0)
    assert ("add", cid) in events and ("remove", cid) in events
    manager.stop()


def test_lifecycle_crash_detected_via_registrar(runtime):
    Registrar(runtime=runtime, primary_search_timeout=0.05)
    manager, clients = _fleet(runtime)
    cid = manager.create_client()
    assert run_until(runtime, lambda: manager.client_count() == 1,
                     timeout=5.0)
    from aiko_services_tpu.services.share import services_cache_singleton
    cache = services_cache_singleton(runtime)
    worker_topic = clients[cid].topic_path
    assert run_until(runtime,
                     lambda: cache.registry.get(worker_topic) is not None,
                     timeout=5.0)

    # Simulate a crash: the worker vanishes without a handshake --
    # deregistration reaches the manager via the registrar event stream.
    worker = clients[cid]
    worker.stop()
    runtime.remove_service(worker.service_id)
    assert run_until(runtime, lambda: manager.client_count() == 0,
                     timeout=5.0)
    manager.stop()


def test_lifecycle_handshake_timeout(runtime):
    Registrar(runtime=runtime, primary_search_timeout=0.05)
    events = []
    manager = LifeCycleManager(
        launcher=lambda cid, topic: None,        # never starts anything
        handshake_lease_time=0.2, runtime=runtime,
        client_change_handler=lambda ev, cid: events.append(ev))
    manager.create_client()
    assert run_until(runtime, lambda: "handshake_timeout" in events,
                     timeout=5.0)
    assert manager.client_count() == 0
    manager.stop()
