"""Native tensor transport (native/tensor_pipe.cpp + ctypes binding):
typed/shaped array round trips over real TCP sockets, the drop-oldest
backlog policy, and a cross-"host" pipeline hop through the tensor://
scheme -- the framework's own replacement for the reference's libzmq
data plane (reference elements/media/scheme_zmq.py:40)."""

import queue

import jax.numpy as jnp
import numpy as np

from conftest import run_until
from aiko_services_tpu.pipeline import Pipeline
from aiko_services_tpu.transport.tensor_pipe import (TensorPipeClient,
                                                     TensorPipeServer)


def test_round_trip_dtypes_and_shapes():
    with TensorPipeServer() as server:
        with TensorPipeClient("127.0.0.1", server.port) as client:
            cases = [
                np.arange(24, dtype=np.int32).reshape(2, 3, 4),
                np.linspace(0, 1, 7, dtype=np.float32),
                np.zeros((0,), dtype=np.float64),          # empty
                np.asarray(jnp.ones((4, 5), jnp.bfloat16)),
                np.random.default_rng(0).integers(
                    0, 255, (480, 640, 3)).astype(np.uint8),  # ~1 MB
            ]
            for i, case in enumerate(cases):
                client.send(case, name=f"case{i}")
            for i, case in enumerate(cases):
                name, got = server.recv(timeout=5.0)
                assert name == f"case{i}"
                assert got.dtype == case.dtype
                assert got.shape == case.shape
                np.testing.assert_array_equal(got, case)


def test_multiple_senders_fan_in():
    with TensorPipeServer() as server:
        clients = [TensorPipeClient("127.0.0.1", server.port)
                   for _ in range(3)]
        for i, client in enumerate(clients):
            client.send(np.full((4,), i, np.int32), name=f"s{i}")
        got = sorted(server.recv(timeout=5.0)[0] for _ in range(3))
        assert got == ["s0", "s1", "s2"]
        for client in clients:
            client.close()


def test_backlog_drops_oldest():
    """Under backlog the NEWEST frames survive (drop-oldest policy).
    Whether any drop happens at all depends on reader scheduling, so
    the assertions are order/newest-kept, not an exact count."""
    with TensorPipeServer(queue_depth=4) as server:
        with TensorPipeClient("127.0.0.1", server.port) as client:
            for i in range(12):
                client.send(np.asarray([i], np.int32))
            survivors = []
            while True:
                frame = server.recv(timeout=1.0)
                if frame is None:
                    break
                survivors.append(int(frame[1][0]))
            assert survivors                       # something arrived
            assert survivors[-1] == 11             # newest kept
            assert survivors == sorted(survivors)  # order preserved


def test_send_to_closed_server_raises():
    server = TensorPipeServer()
    client = TensorPipeClient("127.0.0.1", server.port)
    server.close()
    try:
        for _ in range(64):             # until the RST lands
            client.send(np.zeros((1024,), np.float32))
        raised = False
    except ConnectionError:
        raised = True
    client.close()
    assert raised


def test_pipeline_hop_over_tensor_scheme(runtime):
    """Producer pipeline -> tensor://127.0.0.1 -> consumer pipeline:
    the cross-host hop through the real engine, arrays arriving typed
    and shaped."""
    import tests_media_helpers
    collected = tests_media_helpers.SINK = []
    consumer = Pipeline({
        "version": 0, "name": "p_consumer", "runtime": "jax",
        "graph": ["(RX (Grab (image: tensor)))"],
        "parameters": {},
        "elements": [
            {"name": "RX", "input": [],
             "output": [{"name": "tensor"}, {"name": "name"}],
             "deploy": {"local": {
                 "module": "aiko_services_tpu.elements.scheme_tensor",
                 "class_name": "TensorReadPipe"}},
             "parameters": {"data_sources": "tensor://127.0.0.1:0"}},
            {"name": "Grab", "input": [{"name": "image"}],
             "output": [],
             "deploy": {"local": {"module": "tests_media_helpers",
                                  "class_name": "Collect"}},
             "parameters": {}},
        ]}, runtime=runtime)
    stream = consumer.create_stream_local("rx")
    assert stream is not None
    port = stream.variables["tensor_pipe_port"]
    producer = Pipeline({
        "version": 0, "name": "p_producer", "runtime": "jax",
        "graph": ["(TX)"],
        "parameters": {},
        "elements": [
            {"name": "TX", "input": [{"name": "tensor"}],
             "output": [{"name": "tensor"}],
             "deploy": {"local": {
                 "module": "aiko_services_tpu.elements.scheme_tensor",
                 "class_name": "TensorWritePipe"}},
             "parameters": {"data_targets":
                            f"tensor://127.0.0.1:{port}"}},
        ]}, runtime=runtime)
    responses = queue.Queue()
    tx_stream = producer.create_stream_local("tx",
                                             queue_response=responses)
    payload = jnp.arange(12, dtype=jnp.float32).reshape(3, 4)
    producer.create_frame_local(tx_stream, {"tensor": payload})
    assert run_until(runtime, lambda: len(collected) >= 1, timeout=20.0)
    received = np.asarray(collected[0])
    assert received.shape == (3, 4)
    np.testing.assert_array_equal(received, np.asarray(payload))
    consumer.destroy_stream("rx")
    producer.destroy_stream("tx")


def test_hostname_resolution():
    """tensor://localhost works: names resolve Python-side before the
    numeric-IPv4-only C library sees them (ADVICE r3)."""
    with TensorPipeServer(host="localhost") as server:
        with TensorPipeClient("localhost", server.port) as client:
            client.send(np.asarray([42], np.int32), name="dns")
            name, got = server.recv(timeout=5.0)
            assert name == "dns" and int(got[0]) == 42


def test_unresolvable_host_diagnostic():
    try:
        TensorPipeClient("no-such-host.invalid", 1)
        raised = False
    except ConnectionError as error:
        raised = "resolve" in str(error)
    assert raised


def test_recv_timeout_semantics():
    """timeout=0 polls without blocking; timeout=None blocks (bounded
    here by sending first)."""
    with TensorPipeServer() as server:
        assert server.recv(timeout=0) is None      # empty: instant None
        with TensorPipeClient("127.0.0.1", server.port) as client:
            client.send(np.asarray([7], np.int32))
            name, got = server.recv()              # blocks until frame
            assert int(got[0]) == 7


def test_oversized_payload_drops_connection():
    """A frame advertising more than max_payload drops the CONNECTION
    before any allocation (ADVICE r3: cap peer-driven allocations);
    a fresh connection still works."""
    with TensorPipeServer(max_payload=1024) as server:
        with TensorPipeClient("127.0.0.1", server.port) as client:
            try:
                client.send(np.zeros(4096, np.uint8))  # 4 KB > 1 KB cap
            except ConnectionError:
                pass    # server RSTs mid-send once it sees the advert:
                        # a legitimate outcome of the drop policy
            assert server.recv(timeout=0.5) is None
        with TensorPipeClient("127.0.0.1", server.port) as client:
            client.send(np.zeros(16, np.uint8), name="ok")
            frame = server.recv(timeout=5.0)
            assert frame is not None and frame[0] == "ok"
