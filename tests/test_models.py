"""Model + mesh tests on the 8-device virtual CPU mesh: llama math,
sharded train step, continuous batching."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiko_services_tpu.models import llama, ContinuousBatcher, Request
from aiko_services_tpu.models.tokenizer import ByteTokenizer
from aiko_services_tpu.parallel import MeshPlan, make_mesh, submesh, P


@pytest.fixture(scope="module")
def tiny():
    config = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), config)
    return config, params


def test_prefill_decode_consistency(tiny):
    """Prefill of N+1 tokens == prefill N + decode 1 (same logits)."""
    config, params = tiny
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 9), 0,
                                config.vocab_size)
    full_cache = llama.init_cache(config, 1, 32)
    full_logits, _ = llama.prefill(params, config, tokens, full_cache,
                                   jnp.zeros(1, dtype=jnp.int32))

    cache = llama.init_cache(config, 1, 32)
    _, cache = llama.prefill(params, config, tokens[:, :8], cache,
                             jnp.zeros(1, dtype=jnp.int32))
    decode_logits, _ = llama.decode_step(
        params, config, tokens[:, 8], cache,
        jnp.full((1,), 8, dtype=jnp.int32))
    # bf16 logits; decode's two-part softmax (attention_decode_append)
    # accumulates in a different order than prefill, so agreement is a
    # few bf16 ulps.  Exact-semantics coverage is the float32 variant
    # below.
    np.testing.assert_allclose(
        np.asarray(full_logits[:, -1], dtype=np.float32),
        np.asarray(decode_logits, dtype=np.float32), atol=5e-2)


def test_prefill_decode_consistency_f32():
    """Same consistency check in float32: tight tolerance proves the
    append-form decode attention is semantically exact, not just close
    in bf16."""
    import dataclasses

    config = dataclasses.replace(
        llama.LlamaConfig.tiny(vocab_size=256, max_seq=32),
        dtype="float32")
    params = llama.init_params(jax.random.PRNGKey(0), config)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 9), 0,
                                config.vocab_size)
    full_logits, _ = llama.prefill(
        params, config, tokens, llama.init_cache(config, 1, 32),
        jnp.zeros(1, dtype=jnp.int32))
    cache = llama.init_cache(config, 1, 32)
    _, cache = llama.prefill(params, config, tokens[:, :8], cache,
                             jnp.zeros(1, dtype=jnp.int32))
    decode_logits, _ = llama.decode_step(
        params, config, tokens[:, 8], cache,
        jnp.full((1,), 8, dtype=jnp.int32))
    np.testing.assert_allclose(
        np.asarray(full_logits[:, -1], dtype=np.float32),
        np.asarray(decode_logits, dtype=np.float32), atol=1e-4)


def test_mesh_construction():
    mesh = make_mesh({"dp": 2, "tp": 4})
    assert mesh.shape == {"dp": 2, "tp": 4}
    mesh2 = make_mesh({"dp": -1, "tp": 2})
    assert mesh2.shape["dp"] == 4
    sub = submesh(mesh, "dp", 0)
    assert sub.devices.size == 4
    with pytest.raises(ValueError):
        make_mesh({"dp": 3, "tp": 3})


def test_meshplan_filters_absent_axes():
    plan = MeshPlan.build({"dp": 8})
    sharding = plan.shard(P("dp", "tp", None))     # tp absent -> dropped
    assert sharding.spec == P("dp", None, None)


def test_sharded_prefill_on_mesh(tiny):
    """Params in TP layout on a 2x2x2 mesh; prefill runs under jit with
    sharded inputs and produces the same logits as single-device."""
    config, params = tiny
    plan = MeshPlan.build({"dp": 2, "fsdp": 2, "tp": 2})
    sharded_params = plan.put(params, llama.partition_specs(config))
    cache_sharding = jax.tree_util.tree_map(
        plan.shard, llama.cache_specs())
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0,
                                config.vocab_size)
    cache = jax.device_put(llama.init_cache(config, 2, 32),
                           cache_sharding)
    logits, _ = llama.prefill(sharded_params, config,
                              jax.device_put(tokens,
                                             plan.shard(P("dp", None))),
                              cache, jnp.zeros(2, dtype=jnp.int32))

    ref_cache = llama.init_cache(config, 2, 32)
    ref_logits, _ = llama.prefill(params, config, tokens, ref_cache,
                                  jnp.zeros(2, dtype=jnp.int32))
    # bf16 matmuls reduce in different orders across the tp/fsdp split;
    # tolerance sized to observed noise (~0.06 on logits of O(1-10)).
    np.testing.assert_allclose(np.asarray(logits, dtype=np.float32),
                               np.asarray(ref_logits, dtype=np.float32),
                               atol=1.5e-1)


def test_sharded_train_step(tiny):
    from aiko_services_tpu.models.train import (make_train_step,
                                                init_train_state)
    config, _ = tiny
    plan = MeshPlan.build({"dp": 2, "fsdp": 2, "tp": 2})
    params, opt_state, optimizer = init_train_state(
        jax.random.PRNGKey(0), config, plan)
    step = make_train_step(config, plan, optimizer=optimizer)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (4, 16), 0,
                                config.vocab_size)
    params, opt_state, loss1 = step(params, opt_state, tokens)
    params, opt_state, loss2 = step(params, opt_state, tokens)
    assert float(loss2) < float(loss1)      # it learns the batch
    assert np.isfinite(float(loss1))


def test_continuous_batching(tiny):
    config, params = tiny
    tok = ByteTokenizer()
    batcher = ContinuousBatcher(params, config, max_slots=4, max_seq=64,
                                prefill_chunk=16)
    emitted = {}

    def emit(request_id, token, finished):
        emitted.setdefault(request_id, []).append((token, finished))

    for i in range(6):      # more requests than slots: queueing + reuse
        batcher.submit(Request(
            request_id=f"r{i}",
            prompt_tokens=tok.encode(f"hello {i}"),
            max_new_tokens=5, emit=emit))
    steps = batcher.run_until_drained(max_steps=500)
    assert steps < 500
    assert len(emitted) == 6
    for request_id, tokens in emitted.items():
        assert len(tokens) == 5
        assert tokens[-1][1] is True            # finished flag on last
        assert not any(f for _, f in tokens[:-1])
    assert batcher.active_count == 0 and batcher.queue_depth == 0
    assert batcher.tokens_emitted == 30


def test_chunked_prefill_matches_single_chunk(tiny):
    """A prompt admitted over several prefill chunks must produce exactly
    the tokens a one-chunk admission produces (greedy)."""
    config, params = tiny
    prompt = list(range(1, 29))        # 28 tokens

    def run(chunk):
        out = []
        batcher = ContinuousBatcher(params, config, max_slots=2,
                                    max_seq=64, prefill_chunk=chunk)
        batcher.submit(Request("r", list(prompt), max_new_tokens=8,
                               emit=lambda r, t, f: out.append(t)))
        batcher.run_until_drained(max_steps=200)
        return out

    assert run(8) == run(64)           # 4 chunks vs 1 chunk

def test_prefill_admission_does_not_stall_decode(tiny):
    """While a long prompt admits chunk-by-chunk, an active generation
    must keep emitting a token on (almost) every step -- the head-of-line
    property the chunked/interleaved design exists for."""
    config, params = tiny
    ticks = []
    batcher = ContinuousBatcher(params, config, max_slots=2, max_seq=256,
                                prefill_chunk=8)
    batcher.submit(Request("active", [1, 2], max_new_tokens=60,
                           emit=lambda r, t, f: ticks.append(
                               ("active", batcher.steps))))
    batcher.step()                     # admit + prefill + first decode
    batcher.step()
    # Now admit a prompt needing 6 chunks of prefill.
    batcher.submit(Request("late", list(range(1, 48)), max_new_tokens=4,
                           emit=lambda r, t, f: ticks.append(
                               ("late", batcher.steps))))
    for _ in range(8):                 # the admission window
        batcher.step()
    active_steps = [s for who, s in ticks if who == "active"]
    # One emission per decode tick throughout the admission window: no
    # step gap wider than 1 (a stalled design would show a 6-step hole).
    gaps = [b - a for a, b in zip(active_steps, active_steps[1:])]
    assert gaps and max(gaps) <= 1
    batcher.run_until_drained(max_steps=300)
    assert [who for who, _ in ticks].count("late") == 4

def test_batching_interleaves_long_and_short(tiny):
    """A long generation must not block later short ones (continuous
    batching, not static)."""
    config, params = tiny
    order = []
    batcher = ContinuousBatcher(params, config, max_slots=2, max_seq=64,
                                prefill_chunk=16)
    batcher.submit(Request("long", [1, 2, 3], max_new_tokens=40,
                           emit=lambda r, t, f: order.append((r, f))))
    batcher.submit(Request("short1", [4, 5], max_new_tokens=3,
                           emit=lambda r, t, f: order.append((r, f))))
    batcher.submit(Request("short2", [6], max_new_tokens=3,
                           emit=lambda r, t, f: order.append((r, f))))
    batcher.run_until_drained(max_steps=500)
    finish_order = [r for r, f in order if f]
    assert finish_order.index("short1") < finish_order.index("long")
    assert finish_order.index("short2") < finish_order.index("long")


def test_prefill_into_slot_flash_matches_dense():
    """The Pallas flash prefill (interpret mode on CPU) produces the
    same logits and cache as the dense path for chunked admission."""
    import dataclasses

    base = dataclasses.replace(
        llama.LlamaConfig.tiny(vocab_size=256, max_seq=64),
        dtype="float32")       # f32: any mismatch is semantic, not ulps
    params = llama.init_params(jax.random.PRNGKey(0), base)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, 256)

    results = {}
    for impl in ("dense", "flash"):
        config = dataclasses.replace(base, attention=impl)
        cache = llama.init_cache(config, 2, 64)
        # Two chunks into slot 1, second offset by the first's length.
        logits1, cache = llama.prefill_into_slot(
            params, config, tokens[:, :8], cache, jnp.int32(1),
            jnp.int32(0))
        logits2, cache = llama.prefill_into_slot(
            params, config, tokens[:, 8:], cache, jnp.int32(1),
            jnp.int32(8))
        results[impl] = (np.asarray(logits2, dtype=np.float32),
                         np.asarray(cache["k"], dtype=np.float32))

    np.testing.assert_allclose(results["dense"][0], results["flash"][0],
                               atol=1e-4)
    np.testing.assert_allclose(results["dense"][1], results["flash"][1],
                               atol=1e-4)


def test_decode_block_matches_single_steps(tiny):
    """decode_block=K (fused device loop) emits exactly the token
    streams decode_block=1 produces (greedy), including requests whose
    budgets end mid-block (overshoot discarded) and staggered lengths."""
    from aiko_services_tpu.models import ContinuousBatcher, Request
    from aiko_services_tpu.models.tokenizer import ByteTokenizer

    config, params = tiny
    tok = ByteTokenizer()

    def run(block):
        out = {}
        batcher = ContinuousBatcher(params, config, max_slots=4,
                                    max_seq=64, prefill_chunk=16,
                                    decode_block=block)
        for i, budget in enumerate((5, 9, 4)):     # none divisible by 4
            batcher.submit(Request(
                f"r{i}", tok.encode(f"prompt {i}"),
                max_new_tokens=budget,
                emit=lambda r, t, f: out.setdefault(r, []).append(t)))
        steps = batcher.run_until_drained(max_steps=500)
        assert steps < 500
        assert batcher.active_count == 0
        return out

    single = run(1)
    blocked = run(4)
    assert single == blocked
    assert [len(v) for v in blocked.values()] == [5, 9, 4]


def test_batched_admission_burst_capped(tiny):
    """Batched admission advances at most _ADMISSION_BURST_MAX slots per
    tick: compile buckets stay {1,2,4,8} for ANY max_slots (a wide
    max_slots must not introduce 16/32-row prefill compile shapes),
    with the overflow admitted on following ticks -- every request
    still completes."""
    from aiko_services_tpu.models.batching import _ADMISSION_BURST_MAX

    config, params = tiny
    tok = ByteTokenizer()
    out: dict = {}
    batcher = ContinuousBatcher(params, config, max_slots=20, max_seq=64,
                                prefill_chunk=16, decode_block=4,
                                inflight=2)
    for i in range(20):
        batcher.submit(Request(f"r{i}", tok.encode(f"burst {i}"),
                               max_new_tokens=20,
                               emit=lambda r, t, f:
                               out.setdefault(r, []).append(t)))
    for expected in (8, 16, 20):         # one burst of <= 8 per tick
        batcher.step()
        assert int(np.sum(batcher.decoding)) == expected
    steps = batcher.run_until_drained(max_steps=300)
    assert steps < 300
    assert len(out) == 20
    assert all(len(tokens) == 20 for tokens in out.values())


def test_cancel_frees_slot_and_stops_emits(tiny):
    """ADVICE r4: cancel() removes a queued request, frees an admitted
    request's slot immediately, and suppresses every later emit for it
    -- including tokens for it inside already-in-flight fused blocks."""
    config, params = tiny
    tok = ByteTokenizer()
    out: dict = {}

    def emit(r, t, f):
        out.setdefault(r, []).append((t, f))

    batcher = ContinuousBatcher(params, config, max_slots=2, max_seq=64,
                                prefill_chunk=16, decode_block=4,
                                inflight=2)
    for i in range(3):                       # r2 queues behind 2 slots
        batcher.submit(Request(f"r{i}", tok.encode(f"cancel {i}"),
                               max_new_tokens=12, emit=emit))
    assert batcher.cancel("r2") is True      # still pending
    assert batcher.queue_depth == 2          # r0, r1 remain queued
    batcher.step()                           # admit + one block in flight
    assert batcher.cancel("r0") is True      # admitted, mid-decode
    emitted_at_cancel = len(out.get("r0", []))
    assert batcher.active_count == 1         # slot freed immediately
    batcher.run_until_drained(max_steps=200)
    assert batcher.cancel("missing") is False
    assert len(out.get("r0", [])) == emitted_at_cancel   # no late emits
    assert "r2" not in out                   # never admitted
    assert [f for _, f in out["r1"]][-1] is True         # r1 unaffected
    assert len(out["r1"]) == 12


def test_pipelined_blocks_match_single_steps(tiny):
    """The in-flight pipelined decode (inflight > 1, device-chained
    dispatches) emits exactly the streams the synchronous single-step
    batcher produces -- including a mid-stream admission into a freed
    slot, an EOS cut mid-block, and queueing beyond max_slots."""
    from aiko_services_tpu.models import ContinuousBatcher, Request
    from aiko_services_tpu.models.tokenizer import ByteTokenizer

    config, params = tiny
    tok = ByteTokenizer()

    def run(block, inflight):
        out = {}
        batcher = ContinuousBatcher(params, config, max_slots=2,
                                    max_seq=64, prefill_chunk=16,
                                    decode_block=block,
                                    inflight=inflight)
        for i, budget in enumerate((7, 18, 5, 11)):   # 4 reqs, 2 slots
            batcher.submit(Request(
                f"r{i}", tok.encode(f"pipelined prompt {i}"),
                max_new_tokens=budget,
                emit=lambda r, t, f: out.setdefault(r, []).append(
                    (t, f))))
        steps = batcher.run_until_drained(max_steps=500)
        assert steps < 500
        assert batcher.active_count == 0
        assert not batcher._inflight
        return out

    reference = run(1, 1)
    pipelined = run(4, 3)
    assert reference == pipelined
    assert [len(v) for v in pipelined.values()] == [7, 18, 5, 11]
    for stream in pipelined.values():               # finished flags
        assert stream[-1][1] is True
        assert not any(f for _, f in stream[:-1])


def test_batched_admission_matches_single():
    """A burst of admissions with very different prompt lengths (1 to
    3 chunks each, batched multi-slot prefill + power-of-two padding)
    writes the same KV cache and delivers the same token BUDGET as
    one-at-a-time synchronous admission (tests/admission_check.py; the
    compared property is the CACHE, not token streams -- the two paths
    are different XLA programs whose ~1-ulp rounding can flip a greedy
    argmax on a random-init near-tie, after which streams legitimately
    diverge).

    Runs in a SUBPROCESS deliberately: in-process, the property is
    intermittently CORRUPTED by an earlier interpret-mode int8 Pallas
    test (bisected to test_flash_decode.py::
    test_flash_int8_matches_dequantized_dense; whole cache rows read
    back wrong by >3.0) -- a jax-0.9 CPU-backend buffer interaction,
    not framework logic.  The check itself additionally pins
    single-threaded GEMMs + highest matmul precision: round 5 found
    fresh processes ALSO flaked ~1-in-7 on a loaded host, because
    multi-threaded Eigen partitioning varies with load and flips
    near-tie argmaxes between the two admission shapes (see
    admission_check.py's docstring)."""
    import pathlib
    import subprocess
    import sys as _sys

    script = pathlib.Path(__file__).with_name("admission_check.py")
    result = subprocess.run(
        [_sys.executable, str(script)], capture_output=True, text=True,
        timeout=600,
        env={"PATH": "/usr/bin:/bin", "HOME": "/tmp",
             "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": str(script.parent.parent),
             "AIKO_LOG_LEVEL": "ERROR"})
    assert result.returncode == 0, result.stdout + result.stderr

def test_pipelined_blocks_respect_eos(tiny):
    """EOS inside an in-flight block truncates the stream and frees the
    slot; speculative tokens already dispatched are discarded."""
    from aiko_services_tpu.models import ContinuousBatcher, Request

    config, params = tiny

    def run(block, inflight):
        out = []
        batcher = ContinuousBatcher(params, config, max_slots=2,
                                    max_seq=64, prefill_chunk=16,
                                    decode_block=block,
                                    inflight=inflight)
        batcher.submit(Request(
            "r", [1, 2, 3], max_new_tokens=40,
            emit=lambda r, t, f: out.append((t, f))))
        batcher.run_until_drained(max_steps=300)
        return out

    reference = run(1, 1)
    eos = reference[4][0]       # make the 5th greedy token the EOS

    def run_eos(block, inflight):
        out = []
        batcher = ContinuousBatcher(params, config, max_slots=2,
                                    max_seq=64, prefill_chunk=16,
                                    decode_block=block,
                                    inflight=inflight)
        batcher.submit(Request(
            "r", [1, 2, 3], max_new_tokens=40, eos_tokens=(eos,),
            emit=lambda r, t, f: out.append((t, f))))
        batcher.run_until_drained(max_steps=300)
        return out

    expected = reference[:4] + [(eos, True)]
    expected = [(t, i == 4) for i, (t, _) in enumerate(expected)]
    assert run_eos(4, 3) == expected
    assert run_eos(1, 1) == expected


def test_decode_block_interleaves_with_admission(tiny):
    """A request submitted while a blocked decode is running still
    admits (prefill chunks interleave between fused-block dispatches)
    and both streams complete."""
    from aiko_services_tpu.models import ContinuousBatcher, Request
    from aiko_services_tpu.models.tokenizer import ByteTokenizer

    config, params = tiny
    tok = ByteTokenizer()
    out = {}
    batcher = ContinuousBatcher(params, config, max_slots=2, max_seq=64,
                                prefill_chunk=8, decode_block=4)
    batcher.submit(Request(
        "first", tok.encode("hello"), max_new_tokens=12,
        emit=lambda r, t, f: out.setdefault(r, []).append(t)))
    for _ in range(2):
        batcher.step()                   # first is generating
    batcher.submit(Request(
        "late", tok.encode("a much longer prompt arriving late"),
        max_new_tokens=6,
        emit=lambda r, t, f: out.setdefault(r, []).append(t)))
    steps = batcher.run_until_drained(max_steps=500)
    assert steps < 500
    assert len(out["first"]) == 12
    assert len(out["late"]) == 6


def test_remat_training_matches_and_microbatching_averages(tiny):
    """LlamaConfig(remat=True) must not change the loss (it only
    re-computes activations in the backward pass), and gradient
    accumulation over microbatches must produce the same first-step
    loss as the full batch (same tokens, averaged grads)."""
    import dataclasses

    from aiko_services_tpu.models.train import (init_train_state,
                                                make_train_step)

    config, _ = tiny
    tokens = jax.random.randint(jax.random.PRNGKey(3), (4, 16), 0,
                                config.vocab_size)

    def first_loss(cfg, accumulate):
        plan = MeshPlan.build({"dp": 2, "fsdp": 2, "tp": 2})
        params, opt_state, optimizer = init_train_state(
            jax.random.PRNGKey(0), cfg, plan)
        step = make_train_step(cfg, plan, optimizer=optimizer,
                               accumulate_steps=accumulate)
        params, opt_state, loss = step(params, opt_state, tokens)
        _, _, loss2 = step(params, opt_state, tokens)
        assert float(loss2) < float(loss)       # still learns
        return float(loss)

    plain = first_loss(config, 1)
    remat = first_loss(dataclasses.replace(config, remat=True), 1)
    accumulated = first_loss(config, 2)
    assert abs(plain - remat) < 1e-2            # identical computation
    # Microbatch average equals batch mean CE up to bf16 noise.
    assert abs(plain - accumulated) < 5e-2
