"""Broker probing + UDP bootstrap discovery (reference
configuration.py:104-186) over real loopback sockets."""

import socket
import threading

from aiko_services_tpu.utils import (
    bootstrap_discover, bootstrap_start, get_mqtt_host,
    mqtt_broker_reachable)
from aiko_services_tpu.utils.misc import find_free_port


def listening_port():
    """A real TCP listener standing in for a broker."""
    server = socket.socket()
    server.bind(("127.0.0.1", 0))
    server.listen(1)
    return server, server.getsockname()[1]


def test_broker_reachable_probe():
    server, port = listening_port()
    try:
        assert mqtt_broker_reachable("127.0.0.1", port, timeout=1.0)
    finally:
        server.close()
    assert not mqtt_broker_reachable("127.0.0.1", port, timeout=0.3)


def test_get_mqtt_host_falls_through_candidate_list(monkeypatch):
    """A dead AIKO_MQTT_HOST is skipped in favor of a live fallback from
    AIKO_MQTT_HOSTS -- the reference's candidate-probing semantics."""
    server, live_port = listening_port()
    dead_port = find_free_port()
    try:
        monkeypatch.setenv("AIKO_MQTT_HOST", "127.0.0.1")
        monkeypatch.setenv("AIKO_MQTT_PORT", str(dead_port))
        monkeypatch.setenv("AIKO_MQTT_HOSTS",
                           f"127.0.0.1:{live_port}")
        server_up, host, port = get_mqtt_host(timeout=0.3)
        assert server_up
        assert (host, port) == ("127.0.0.1", live_port)
    finally:
        server.close()


def test_get_mqtt_host_all_down_reports_primary(monkeypatch):
    dead = find_free_port()
    monkeypatch.setenv("AIKO_MQTT_HOST", "127.0.0.1")
    monkeypatch.setenv("AIKO_MQTT_PORT", str(dead))
    monkeypatch.delenv("AIKO_MQTT_HOSTS", raising=False)
    server_up, host, port = get_mqtt_host(timeout=0.2)
    assert not server_up
    assert (host, port) == ("127.0.0.1", dead)


def test_bootstrap_roundtrip(monkeypatch):
    """boot? broadcast -> boot response carrying broker + namespace."""
    monkeypatch.setenv("AIKO_NAMESPACE", "testspace")
    udp_port = find_free_port(kind="udp")
    stop = bootstrap_start(mqtt_host="broker.local", mqtt_port=1883,
                           bind="127.0.0.1", port=udp_port)
    try:
        result = bootstrap_discover(server="127.0.0.1", port=udp_port,
                                    timeout=3.0)
        assert result == {"host": "broker.local", "port": 1883,
                          "namespace": "testspace"}
    finally:
        stop.set()


def test_bootstrap_discover_timeout():
    assert bootstrap_discover(server="127.0.0.1",
                              port=find_free_port(kind="udp"),
                              timeout=0.3) is None


def test_bootstrap_responder_ignores_garbage(monkeypatch):
    """Malformed datagrams don't kill the responder thread."""
    udp_port = find_free_port(kind="udp")
    stop = bootstrap_start(mqtt_host="h", mqtt_port=1,
                           bind="127.0.0.1", port=udp_port)
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as noise:
            noise.sendto(b"\xff\xfe not a boot request",
                         ("127.0.0.1", udp_port))
            noise.sendto(b"boot? bad", ("127.0.0.1", udp_port))
        result = bootstrap_discover(server="127.0.0.1", port=udp_port,
                                    timeout=3.0)
        assert result is not None and result["host"] == "h"
    finally:
        stop.set()


def test_get_mqtt_host_skips_malformed_entries(monkeypatch):
    server, live_port = listening_port()
    try:
        monkeypatch.delenv("AIKO_MQTT_HOST", raising=False)
        monkeypatch.setenv("AIKO_MQTT_PORT", str(find_free_port()))
        monkeypatch.setenv("AIKO_MQTT_HOSTS",
                           f"broker:1883x, 127.0.0.1:{live_port}")
        server_up, host, port = get_mqtt_host(timeout=0.3)
        assert server_up and (host, port) == ("127.0.0.1", live_port)
    finally:
        server.close()
