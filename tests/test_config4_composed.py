"""The flagship config-4 COMPOSITION: detect -> caption -> LLM with
placement blocks AND async stages, end to end through the real engine on
the 8-device virtual mesh (VERDICT r4 item 4).

The pieces are proven separately (tests/test_tensor.py placement,
tests/test_async_stages.py async park/resume + cross-frame batching);
this is the one test that runs them TOGETHER, the TPU equivalent of the
reference's remote-deploy pipeline parallelism (reference
src/aiko_services/main/pipeline.py:246-258,858-891 -- stages in other
processes; here stages on disjoint chip submeshes with ICI frame hops).
"""

import json
import queue

import numpy as np

from conftest import run_until

from aiko_services_tpu.pipeline import create_pipeline

N_FRAMES = 8
MAX_NEW = 8


def _definition(tmp_path):
    definition = {
        "version": 0, "name": "config4", "runtime": "jax",
        "graph": ["(DET (CAP (LLM)))"],
        "elements": [
            {"name": "DET",
             "input": [{"name": "image"}],
             "output": [{"name": "image"}, {"name": "overlay"},
                        {"name": "detections"}],
             "parameters": {"width": 4, "max_batch": 8},
             "placement": {"mesh": {"dp": 4}},
             "deploy": {"local": {
                 "module": "aiko_services_tpu.elements.detect",
                 "class_name": "Detector"}}},
            {"name": "CAP",
             "input": [{"name": "detections"}],
             "output": [{"name": "text"}],
             "deploy": {"local": {
                 "module": "aiko_services_tpu.elements.llm",
                 "class_name": "DetectionCaption"}}},
            {"name": "LLM",
             "input": [{"name": "text"}],
             "output": [{"name": "text"}],
             "parameters": {"max_new_tokens": MAX_NEW, "max_seq": 64},
             "placement": {"mesh": {"tp": 4}},
             "deploy": {"local": {
                 "module": "aiko_services_tpu.elements.llm",
                 "class_name": "LLM"}}},
        ]}
    path = tmp_path / "config4.json"
    path.write_text(json.dumps(definition))
    return str(path)


def test_config4_placed_async_composition(tmp_path, runtime):
    """detect on a 4-chip dp submesh, LLM on the OTHER 4 chips as tp=4,
    async stages on both ends: every frame completes, detect
    micro-batches the parked burst into fewer device dispatches, and
    the LLM decodes requests from many in-flight frames together --
    frames overlapped at both model stages."""
    pipeline = create_pipeline(_definition(tmp_path), runtime=runtime)

    # -- placement: disjoint submeshes straight from the definition ----
    placement = pipeline.stage_placement
    assert placement is not None
    assert dict(placement.plan("DET").mesh.shape) == {"dp": 4}
    assert dict(placement.plan("LLM").mesh.shape) == {"tp": 4}
    det_devices = set(placement.plan("DET").mesh.devices.flat)
    llm_devices = set(placement.plan("LLM").mesh.devices.flat)
    assert not det_devices & llm_devices

    responses = queue.Queue()
    stream = pipeline.create_stream_local("s", queue_response=responses)
    rng = np.random.default_rng(0)
    for _ in range(N_FRAMES):
        pipeline.create_frame_local(stream, {
            "image": rng.integers(0, 255, (64, 64, 3), dtype=np.uint8)})
    assert run_until(runtime, lambda: responses.qsize() >= N_FRAMES,
                     timeout=300.0)

    texts = []
    while not responses.empty():
        _, _, swag, metrics, okay, diagnostic = responses.get()
        assert okay, diagnostic
        texts.append(swag["text"])
        assert "DET_time" in metrics and "LLM_time" in metrics
    assert len(texts) == N_FRAMES

    # -- placement transfer: the detect element resolved ITS stage's
    # submesh (not the local default) and its weights live there.
    import jax
    det = pipeline.graph.get_node("DET").element
    assert dict(det.plan.mesh.shape) == {"dp": 4}
    for leaf in jax.tree_util.tree_leaves(det._params):
        assert set(leaf.sharding.device_set) <= det_devices

    # -- async composition, detect side: the parked burst ran as
    # MICRO-BATCHED dispatches, not one dispatch per frame.
    dispatches = det.jit_cache.hits + det.jit_cache.misses
    assert dispatches < N_FRAMES, (
        f"{dispatches} detect dispatches for {N_FRAMES} frames: parked "
        "frames were not micro-batched")

    # -- async composition, LLM side: requests from many in-flight
    # frames decoded together (total decode steps far below the
    # serialized sum) -- frames overlapped across the placed stages.
    batcher = pipeline.graph.get_node("LLM").element._batcher
    serialized = N_FRAMES * MAX_NEW
    assert batcher.steps < serialized * 0.6, (
        f"{batcher.steps} decode steps for {N_FRAMES} frames x "
        f"{MAX_NEW} tokens: frames did not overlap at the LLM stage")
    pipeline.stop()
