"""Real-MQTT integration: the in-tree C++ broker (native/
mqtt_broker.cpp) + the stdlib MQTT client (transport/mini_mqtt.py)
carrying the genuine control plane -- registrar election, discovery,
actor RPC, EC share replication, and LWT failure detection over real
TCP sockets (the role mosquitto plays for the reference,
scripts/system_start.sh:28-56)."""

import time

import pytest

from conftest import run_until
from aiko_services_tpu.transport import BrokerProcess
from aiko_services_tpu.transport.mini_mqtt import Client


@pytest.fixture(scope="module")
def broker():
    with BrokerProcess(export_env=False) as instance:
        yield instance


@pytest.fixture
def mqtt_runtime(broker, monkeypatch):
    """Process runtime on the real MQTT transport against the native
    broker."""
    monkeypatch.setenv("AIKO_MQTT_HOST", "127.0.0.1")
    monkeypatch.setenv("AIKO_MQTT_PORT", str(broker.port))
    monkeypatch.delenv("AIKO_MQTT_HOSTS", raising=False)
    from aiko_services_tpu.runtime import init_process, reset_process
    from aiko_services_tpu.services.share import reset_services_cache

    reset_services_cache()
    runtime = init_process(transport="mqtt")
    runtime.initialize()
    yield runtime
    runtime.engine.terminate()
    runtime.message.disconnect()
    reset_process()


# -- raw client <-> broker --------------------------------------------------

def connect_client(broker, on_message=None, will=None):
    client = Client()
    events = {"connected": False}

    def on_connect(*args):
        events["connected"] = True

    client.on_connect = on_connect
    if on_message is not None:
        client.on_message = on_message
    if will is not None:
        client.will_set(*will)
    client.connect_async("127.0.0.1", broker.port)
    client.loop_start()
    deadline = time.time() + 5.0
    while not events["connected"] and time.time() < deadline:
        time.sleep(0.01)
    assert events["connected"], "client never connected"
    return client


def wait_for(predicate, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return predicate()


def test_publish_subscribe_wildcards(broker):
    got = []
    subscriber = connect_client(
        broker, on_message=lambda c, u, m: got.append(
            (m.topic, m.payload.decode())))
    publisher = connect_client(broker)
    subscriber.subscribe("ns/+/state")
    subscriber.subscribe("deep/#")
    time.sleep(0.1)                               # SUBACK round trip
    publisher.publish("ns/a/state", "alpha")
    publisher.publish("ns/a/b/state", "too-deep")
    publisher.publish("deep/x/y/z", "beta")
    assert wait_for(lambda: len(got) >= 2)
    assert ("ns/a/state", "alpha") in got
    assert ("deep/x/y/z", "beta") in got
    assert all(topic != "ns/a/b/state" for topic, _ in got)
    subscriber.disconnect(), publisher.disconnect()
    subscriber.loop_stop(), publisher.loop_stop()


def test_retained_message_and_clear(broker):
    publisher = connect_client(broker)
    publisher.publish("boot/primary", "found", retain=True)
    time.sleep(0.1)
    got = []
    late = connect_client(
        broker, on_message=lambda c, u, m: got.append(m.payload.decode()))
    late.subscribe("boot/#")
    assert wait_for(lambda: "found" in got)       # retained delivery

    publisher.publish("boot/primary", "", retain=True)   # clear
    time.sleep(0.1)
    got2 = []
    later = connect_client(
        broker, on_message=lambda c, u, m: got2.append(m.payload))
    later.subscribe("boot/#")
    time.sleep(0.3)
    assert got2 == []                             # nothing retained
    for client in (publisher, late, later):
        client.disconnect()
        client.loop_stop()


def test_last_will_fires_on_abnormal_disconnect(broker):
    import socket
    import struct

    got = []
    watcher = connect_client(
        broker, on_message=lambda c, u, m: got.append(
            (m.topic, m.payload.decode())))
    watcher.subscribe("ns/+/0/state")
    time.sleep(0.1)

    # Hand-rolled CONNECT with a will, then a hard socket close with no
    # DISCONNECT -- the process-died case LWT exists for.
    def mqtt_string(text):
        return struct.pack(">H", len(text)) + text.encode()

    payload = (mqtt_string("doomed") + mqtt_string("ns/h1/0/state")
               + mqtt_string("(absent)"))
    body = (mqtt_string("MQTT") + bytes([4, 0x02 | 0x04 | 0x20])
            + struct.pack(">H", 60) + payload)
    doomed = socket.create_connection(("127.0.0.1", broker.port))
    doomed.sendall(bytes([0x10, len(body)]) + body)
    assert doomed.recv(4)[:2] == b"\x20\x02"      # CONNACK
    doomed.close()                                # abrupt
    assert wait_for(lambda: ("ns/h1/0/state", "(absent)") in got)
    watcher.disconnect()
    watcher.loop_stop()


# -- full control plane over real MQTT --------------------------------------

def test_control_plane_over_native_broker(mqtt_runtime):
    """Registrar election, actor discovery/RPC, and EC share
    replication run unchanged over the native broker."""
    from aiko_services_tpu.services import (Actor, Registrar,
                                            ServiceFilter, do_command)

    runtime = mqtt_runtime
    Registrar(runtime=runtime, primary_search_timeout=0.2)

    class Greeter(Actor):
        def __init__(self, runtime=None):
            super().__init__("greeter", "greeter:0", runtime=runtime)
            self.greeted = []
            self.share["mood"] = "calm"

        def greet(self, name):
            self.greeted.append(str(name))
            self.ec_producer.update("mood", "happy")

    greeter = Greeter(runtime=runtime)
    done = []
    do_command(runtime, None, ServiceFilter(protocol="greeter"),
               lambda proxy: (proxy.greet("Pele"), done.append(1)))
    assert run_until(runtime, lambda: greeter.greeted == ["Pele"],
                     timeout=15.0), "RPC over MQTT never arrived"

    # EC share: a consumer on the same fabric mirrors the update.
    from aiko_services_tpu.services import ECConsumer
    view = {}
    ECConsumer(runtime, greeter.topic_path, view)
    assert run_until(runtime, lambda: view.get("mood") == "happy",
                     timeout=15.0), "EC share never replicated"


def test_two_processes_over_native_broker(broker, monkeypatch):
    """The real multi-host shape: a Registrar in a SEPARATE OS process
    (via the CLI), discovered and used by this process over the broker
    (reference: aiko_registrar + any client host, joined by mosquitto)."""
    import os
    import pathlib
    import subprocess
    import sys

    monkeypatch.setenv("AIKO_MQTT_HOST", "127.0.0.1")
    monkeypatch.setenv("AIKO_MQTT_PORT", str(broker.port))
    monkeypatch.delenv("AIKO_MQTT_HOSTS", raising=False)
    repo = pathlib.Path(__file__).resolve().parent.parent
    registrar_process = subprocess.Popen(
        [sys.executable, "-m", "aiko_services_tpu", "registrar",
         "-t", "mqtt"],
        cwd=repo,
        env={"PATH": "/usr/bin:/bin", "HOME": "/tmp",
             "AIKO_LOG_LEVEL": "ERROR",
             "AIKO_MQTT_HOST": "127.0.0.1",
             "AIKO_MQTT_PORT": str(broker.port),
             "PYTHONPATH": str(repo)})
    try:
        from aiko_services_tpu.runtime import init_process, reset_process
        from aiko_services_tpu.services import Actor
        from aiko_services_tpu.services.share import reset_services_cache
        from aiko_services_tpu.services.share import \
            services_cache_singleton

        reset_services_cache()
        runtime = init_process(transport="mqtt")
        runtime.initialize()
        try:
            actor = Actor("cross_proc", "cross:0", runtime=runtime)
            cache = services_cache_singleton(runtime)
            # The remote registrar must answer the share query and list
            # our local actor back to us.
            assert run_until(
                runtime,
                lambda: any(r.name == "cross_proc"
                            for r in cache.registry.all()),
                timeout=20.0), "remote registrar never mirrored us"
        finally:
            runtime.engine.terminate()
            runtime.message.disconnect()
            reset_process()
    finally:
        registrar_process.terminate()
        registrar_process.wait(timeout=5.0)


def _raw_connect(broker, client_id, will_topic, keepalive=60):
    """Hand-rolled CONNECT with a will; returns the connected socket."""
    import socket
    import struct

    def mqtt_string(text):
        return struct.pack(">H", len(text)) + text.encode()

    payload = (mqtt_string(client_id) + mqtt_string(will_topic)
               + mqtt_string("(absent)"))
    body = (mqtt_string("MQTT") + bytes([4, 0x02 | 0x04])
            + struct.pack(">H", keepalive) + payload)
    sock = socket.create_connection(("127.0.0.1", broker.port))
    sock.sendall(bytes([0x10, len(body)]) + body)
    assert sock.recv(4)[:2] == b"\x20\x02"        # CONNACK
    return sock


def test_graceful_disconnect_suppresses_will(broker):
    """DISCONNECT followed immediately by close (they arrive as one
    POLLIN|POLLHUP burst) must clear the will (MQTT-3.14.4-3) -- a live
    process cycling its connection to change its will must not be
    declared dead."""
    got = []
    watcher = connect_client(
        broker, on_message=lambda c, u, m: got.append(m.topic))
    watcher.subscribe("grace/+/state")
    time.sleep(0.1)
    polite = _raw_connect(broker, "polite", "grace/p1/state")
    polite.sendall(bytes([0xe0, 0]))              # DISCONNECT
    polite.close()                                # immediately
    time.sleep(0.5)
    assert got == [], "will fired on a graceful disconnect"
    watcher.disconnect()
    watcher.loop_stop()


def test_keepalive_timeout_fires_will(broker):
    """A silently-dead client (no FIN -- e.g. host power loss) is
    detected at 1.5x keepalive and its will fires (mosquitto
    semantics)."""
    got = []
    watcher = connect_client(
        broker, on_message=lambda c, u, m: got.append(m.topic))
    watcher.subscribe("silent/+/state")
    time.sleep(0.1)
    quiet = _raw_connect(broker, "quiet", "silent/h2/state", keepalive=1)
    # Send nothing and keep the socket open: only the keepalive timer
    # can detect this death.
    assert wait_for(lambda: "silent/h2/state" in got, timeout=10.0), \
        "keepalive expiry never fired the will"
    quiet.close()
    watcher.disconnect()
    watcher.loop_stop()


def test_pipeline_update_cli_over_broker(broker):
    """`pipeline update NAME -p k v -fd ...` finds a running pipeline by
    name over the fabric and live-updates it (reference `aiko_pipeline
    update`)."""
    import pathlib
    import subprocess
    import sys

    repo = pathlib.Path(__file__).resolve().parent.parent
    env = {"PATH": "/usr/bin:/bin", "HOME": "/tmp",
           "AIKO_LOG_LEVEL": "INFO", "PYTHONPATH": str(repo),
           "JAX_PLATFORMS": "cpu",
           "AIKO_MQTT_HOST": "127.0.0.1",
           "AIKO_MQTT_PORT": str(broker.port)}
    registrar = subprocess.Popen(
        [sys.executable, "-m", "aiko_services_tpu", "registrar",
         "-t", "mqtt"], cwd=repo, env=env)
    create = subprocess.Popen(
        [sys.executable, "-m", "aiko_services_tpu", "pipeline", "create",
         "examples/pipeline/pipeline_local.json", "-t", "mqtt"],
        cwd=repo, env=env, stderr=subprocess.DEVNULL)
    try:
        # No-op update refused before any network traffic.
        noop = subprocess.run(
            [sys.executable, "-m", "aiko_services_tpu", "pipeline",
             "update", "p_local", "-t", "mqtt"],
            cwd=repo, env=env, capture_output=True, text=True,
            timeout=60)
        assert noop.returncode != 0
        assert "nothing to update" in noop.stderr

        update = subprocess.run(
            [sys.executable, "-m", "aiko_services_tpu", "pipeline",
             "update", "p_local", "-t", "mqtt", "-p", "note", "hello",
             "-fd", "(x: 7)", "--timeout", "15"],
            cwd=repo, env=env, capture_output=True, text=True,
            timeout=60)
        assert update.returncode == 0, update.stderr[-1500:]
        assert "update sent" in update.stdout

        # End-to-end effect check: a response-routed frame over the
        # same wire command the CLI used executes in the remote
        # pipeline and answers with the computed result
        # (2x + x^2 at x=7 -> 63).  The pipeline's topic path comes
        # from the CLI's own "update sent to <topic>" report.
        topic_path = update.stdout.strip().rsplit(" ", 1)[-1]
        got = []
        observer = connect_client(
            broker, on_message=lambda c, u, m: got.append(
                m.payload.decode()))
        response_topic = "test/update/response"
        observer.subscribe(response_topic)
        time.sleep(0.2)
        observer.publish(
            f"{topic_path}/in",
            "(process_frame (stream_id: 2 response_topic: "
            f"{response_topic}) (x: 7))")
        assert wait_for(lambda: any("63" in p for p in got),
                        timeout=15.0), got
        observer.disconnect()
        observer.loop_stop()
    finally:
        create.terminate()
        create.wait(timeout=5.0)
        registrar.terminate()
        registrar.wait(timeout=5.0)


def test_pipeline_create_hooks_flag(tmp_path):
    """--hooks pf,pe attaches the printing handler; bad names rejected."""
    import pathlib
    import subprocess
    import sys

    repo = pathlib.Path(__file__).resolve().parent.parent
    env = {"PATH": "/usr/bin:/bin", "HOME": "/tmp",
           "AIKO_LOG_LEVEL": "INFO", "PYTHONPATH": str(repo),
           "JAX_PLATFORMS": "cpu"}
    bad = subprocess.run(
        [sys.executable, "-m", "aiko_services_tpu", "pipeline", "create",
         "examples/pipeline/pipeline_local.json", "-t", "loopback",
         "--hooks", "bogus"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=60)
    assert bad.returncode != 0
    assert "unknown hooks" in bad.stderr

    good = subprocess.run(
        ["timeout", "--signal=INT", "10", sys.executable, "-m",
         "aiko_services_tpu", "pipeline", "create",
         "examples/pipeline/pipeline_local.json", "-t", "loopback",
         "-s", "1", "-fd", "(x: 1)", "--hooks", "pf,pe"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=60)
    assert "HOOK pipeline.process_frame:0" in good.stderr
    assert "HOOK pipeline.process_element:0" in good.stderr


def test_system_start_status_reset_stop(tmp_path):
    """The system lifecycle CLI (reference scripts/system_*.sh): start
    launches broker+registrar detached, status probes, reset clears the
    retained election record, stop tears down."""
    import json as json_module
    import pathlib
    import subprocess
    import sys
    import time as time_module

    repo = pathlib.Path(__file__).resolve().parent.parent
    env = {"PATH": "/usr/bin:/bin", "HOME": "/tmp",
           "AIKO_LOG_LEVEL": "ERROR", "PYTHONPATH": str(repo),
           "AIKO_STATE_DIR": str(tmp_path)}

    def cli(*args, **kwargs):
        return subprocess.run(
            [sys.executable, "-m", "aiko_services_tpu", *args],
            cwd=repo, env=env, capture_output=True, text=True,
            timeout=60, **kwargs)

    start = cli("system", "start", "--port", "0")
    assert start.returncode == 0, start.stderr[-1500:]
    state = json_module.loads(
        (tmp_path / "aiko_tpu_system.json").read_text())
    try:
        status = cli("system", "status")
        assert f":{state['port']} up" in status.stdout

        # Double start refused.
        again = cli("system", "start")
        assert again.returncode != 0
        assert "already started" in again.stderr

        # The fabric actually works: a client process finds the
        # registrar started by `system start`.
        env_mqtt = dict(env, AIKO_MQTT_HOST="127.0.0.1",
                        AIKO_MQTT_PORT=str(state["port"]),
                        JAX_PLATFORMS="cpu")
        listing = subprocess.run(
            [sys.executable, "-m", "aiko_services_tpu", "pipeline",
             "list", "-t", "mqtt", "--timeout", "15"],
            cwd=repo, env=env_mqtt, capture_output=True, text=True,
            timeout=60)
        assert listing.returncode == 0
        assert "no registrar found" not in listing.stderr

        reset = subprocess.run(
            [sys.executable, "-m", "aiko_services_tpu", "system",
             "reset", "-t", "mqtt"],
            cwd=repo, env=env_mqtt, capture_output=True, text=True,
            timeout=60)
        assert reset.returncode == 0
        assert "cleared retained" in reset.stdout
    finally:
        stop = cli("system", "stop")
    assert stop.returncode == 0, stop.stderr[-500:]
    assert not (tmp_path / "aiko_tpu_system.json").exists()
    # Processes actually died (kill(pid, 0) succeeds on zombies when no
    # reaper has collected the orphans, so read /proc state instead).
    time_module.sleep(0.3)
    for key in ("broker_pid", "registrar_pid"):
        stat = pathlib.Path(f"/proc/{state[key]}/stat")
        if stat.exists():
            proc_state = stat.read_text().rsplit(")", 1)[1].split()[0]
            assert proc_state == "Z", f"{key} still running"
    # And the broker port no longer answers.
    from aiko_services_tpu.utils import mqtt_broker_reachable
    assert not mqtt_broker_reachable("127.0.0.1", state["port"],
                                     timeout=0.5)


def test_transport_reconnects_after_broker_restart(monkeypatch):
    """Broker dies and comes back on the same port: the MQTT transport's
    network loop reconnects with backoff and its on_connect re-subscribes
    every tracked topic, so delivery resumes without application action
    (raw mini_mqtt.Client deliberately leaves re-subscription to
    on_connect, paho-style)."""
    from aiko_services_tpu.transport.mqtt import MQTTMessage
    from aiko_services_tpu.utils.misc import find_free_port

    port = find_free_port()
    first = BrokerProcess(port=port, export_env=False).start()
    monkeypatch.setenv("AIKO_MQTT_HOST", "127.0.0.1")
    monkeypatch.setenv("AIKO_MQTT_PORT", str(port))
    monkeypatch.delenv("AIKO_MQTT_HOSTS", raising=False)

    got = []
    transport = MQTTMessage(
        message_handler=lambda topic, payload: got.append(str(payload)))
    second = None
    try:
        transport.subscribe("restart/topic")
        transport.connect()
        publisher = connect_client(first)
        publisher.publish("restart/topic", "before")
        okay = wait_for(lambda: "before" in got)
        publisher.disconnect()
        publisher.loop_stop()
        assert okay

        first.stop()                               # broker gone
        time.sleep(0.5)
        second = BrokerProcess(port=port, export_env=False).start()
        publisher = connect_client(second)
        deadline = time.time() + 15.0
        while time.time() < deadline and "after" not in got:
            publisher.publish("restart/topic", "after")
            time.sleep(0.25)
        assert "after" in got, "transport never recovered delivery"
        publisher.disconnect()
        publisher.loop_stop()
    finally:
        transport.disconnect()
        first.stop()                               # no-op if stopped
        if second is not None:
            second.stop()
