"""Pretrained-weight ingestion: fabricated HF-layout safetensors ->
scanned pytree -> orbax checkpoint -> serving element (reference
equivalent: drop-in pretrained model usage, examples/yolo/yolo.py:47-50)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from aiko_services_tpu.models import convert, llama
from aiko_services_tpu.models import detector as detector_model


def _fabricate_hf_llama(config: llama.LlamaConfig, seed=0) -> dict:
    """Random tensors in the HF Llama naming/layout ([out, in] Linears)."""
    rng = np.random.default_rng(seed)
    c = config
    hd = c.head_dim

    def t(*shape):
        return rng.standard_normal(shape).astype(np.float32) * 0.02

    tensors = {"model.embed_tokens.weight": t(c.vocab_size, c.dim),
               "model.norm.weight": np.ones(c.dim, np.float32),
               "lm_head.weight": t(c.vocab_size, c.dim)}
    for i in range(c.n_layers):
        p = f"model.layers.{i}"
        tensors.update({
            f"{p}.self_attn.q_proj.weight": t(c.n_heads * hd, c.dim),
            f"{p}.self_attn.k_proj.weight": t(c.n_kv_heads * hd, c.dim),
            f"{p}.self_attn.v_proj.weight": t(c.n_kv_heads * hd, c.dim),
            f"{p}.self_attn.o_proj.weight": t(c.dim, c.n_heads * hd),
            f"{p}.mlp.gate_proj.weight": t(c.hidden_dim, c.dim),
            f"{p}.mlp.up_proj.weight": t(c.hidden_dim, c.dim),
            f"{p}.mlp.down_proj.weight": t(c.dim, c.hidden_dim),
            f"{p}.input_layernorm.weight": np.ones(c.dim, np.float32),
            f"{p}.post_attention_layernorm.weight":
                np.ones(c.dim, np.float32)})
    return tensors


def _save_safetensors(path, tensors):
    from safetensors.numpy import save_file
    save_file(tensors, str(path))


def test_llama_roundtrip_through_checkpoint(tmp_path, runtime):
    """Fabricated safetensors -> convert_llama -> LLMService(checkpoint=)
    generates, and the converted projections equal the transposed HF
    tensors."""
    config = llama.LlamaConfig.tiny(vocab_size=128, max_seq=64)
    tensors = _fabricate_hf_llama(config)
    src = tmp_path / "model.safetensors"
    _save_safetensors(src, tensors)

    ckpt = tmp_path / "converted"
    out_config = convert.convert_llama(src, ckpt, config)
    assert out_config is config

    # Layout: wq[layer] == q_proj[layer].T
    params = convert.llama_params_from_hf(
        convert.load_safetensors(src), config)
    np.testing.assert_allclose(
        np.asarray(params["layers"]["wq"][1], np.float32),
        tensors["model.layers.1.self_attn.q_proj.weight"].T,
        rtol=1e-2, atol=1e-2)  # bf16 cast
    np.testing.assert_allclose(
        np.asarray(params["unembed"], np.float32),
        tensors["lm_head.weight"].T, rtol=1e-2, atol=1e-2)

    from aiko_services_tpu.elements import LLMService
    service = LLMService(runtime=runtime, config=config,
                         checkpoint=str(ckpt))
    text = service.generate_local("ab", max_new_tokens=4)
    assert isinstance(text, str)
    # The served params are the converted ones, not random init.
    np.testing.assert_array_equal(
        np.asarray(service.batcher.params["layers"]["wk"]),
        np.asarray(params["layers"]["wk"]))


def test_llama_tied_embeddings_and_sharded_dir(tmp_path):
    """lm_head absent -> unembed = embed.T; shards in a directory merge."""
    config = llama.LlamaConfig.tiny(vocab_size=64, max_seq=32)
    tensors = _fabricate_hf_llama(config)
    del tensors["lm_head.weight"]
    shard_dir = tmp_path / "shards"
    shard_dir.mkdir()
    names = sorted(tensors)
    half = len(names) // 2
    _save_safetensors(shard_dir / "model-00001.safetensors",
                      {n: tensors[n] for n in names[:half]})
    _save_safetensors(shard_dir / "model-00002.safetensors",
                      {n: tensors[n] for n in names[half:]})

    params = convert.llama_params_from_hf(
        convert.load_safetensors(shard_dir), config)
    np.testing.assert_allclose(
        np.asarray(params["unembed"], np.float32),
        np.asarray(params["embed"], np.float32).T, rtol=1e-6)


def test_infer_llama_config_from_shapes():
    config = llama.LlamaConfig(vocab_size=128, dim=64, n_layers=3,
                               n_heads=32, n_kv_heads=8, hidden_dim=96,
                               max_seq=64)
    tensors = _fabricate_hf_llama(config)
    inferred = convert.infer_llama_config(tensors)
    assert inferred.vocab_size == 128
    assert inferred.dim == 64
    assert inferred.n_layers == 3
    assert inferred.hidden_dim == 96
    assert inferred.n_heads == 32          # Llama convention default
    assert inferred.n_kv_heads == 8


def test_hf_config_json_overrides_shape_guess(tmp_path):
    """config.json next to the safetensors is authoritative for head
    counts (shapes alone cannot distinguish n_heads)."""
    import json

    config = llama.LlamaConfig.tiny(vocab_size=64, max_seq=32)  # 4 heads
    tensors = _fabricate_hf_llama(config)
    src_dir = tmp_path / "snapshot"
    src_dir.mkdir()
    _save_safetensors(src_dir / "model.safetensors", tensors)
    (src_dir / "config.json").write_text(json.dumps(
        {"num_attention_heads": config.n_heads,
         "num_key_value_heads": config.n_kv_heads,
         "rope_theta": config.rope_theta}))

    out = convert.convert_llama(src_dir, tmp_path / "ckpt", max_seq=32)
    assert out.n_heads == config.n_heads
    assert out.n_kv_heads == config.n_kv_heads
    assert out.rope_theta == config.rope_theta


def test_convert_rejects_wrong_shapes(tmp_path):
    config = llama.LlamaConfig.tiny(vocab_size=128, max_seq=64)
    # Uniformly wrong: every layer's up_proj truncated -> caught by the
    # post-stack shape check, named by pytree path.
    tensors = _fabricate_hf_llama(config)
    for i in range(config.n_layers):
        name = f"model.layers.{i}.mlp.up_proj.weight"
        tensors[name] = tensors[name][:, :-1]
    src = tmp_path / "bad.safetensors"
    _save_safetensors(src, tensors)
    with pytest.raises(ValueError, match="w_up"):
        convert.llama_params_from_hf(convert.load_safetensors(src),
                                     config)

    # Ragged: only layer 0 wrong -> caught at stack time, named by the
    # HF template.
    tensors = _fabricate_hf_llama(config)
    tensors["model.layers.0.mlp.up_proj.weight"] = \
        tensors["model.layers.0.mlp.up_proj.weight"][:, :-1]
    src2 = tmp_path / "ragged.safetensors"
    _save_safetensors(src2, tensors)
    with pytest.raises(ValueError, match="up_proj"):
        convert.llama_params_from_hf(convert.load_safetensors(src2),
                                     config)


def test_detector_roundtrip(tmp_path):
    """Detector export format: pytree paths joined with '.' -> orbax
    checkpoint -> restore equals source."""
    config = detector_model.DetectorConfig.tiny()
    reference = detector_model.init_params(jax.random.PRNGKey(7), config)

    flat = {}

    def collect(path, leaf):
        name = ".".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in path)
        flat[name] = np.asarray(leaf, dtype=np.float32)
        return leaf

    jax.tree_util.tree_map_with_path(collect, reference)
    src = tmp_path / "detector.safetensors"
    _save_safetensors(src, flat)

    ckpt = tmp_path / "det_ckpt"
    convert.convert_detector(src, ckpt, config)

    from aiko_services_tpu.models.checkpoint import maybe_restore
    template = detector_model.init_params(jax.random.PRNGKey(0), config)
    restored = maybe_restore(template, str(ckpt))
    ref_leaves = jax.tree_util.tree_leaves(reference)
    got_leaves = jax.tree_util.tree_leaves(restored)
    for ref, got in zip(ref_leaves, got_leaves):
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(ref, np.float32),
                                   rtol=1e-2, atol=1e-2)
