"""Static analysis (ISSUE 6): the aiko_lint rule catalogue against its
broken-definition fixture corpus, in-tree cleanliness, the framework
self-check (``aiko_lint --self`` as a tier-1 gate), and the
``Pipeline.__init__`` pre-flight."""

import os
import time
from pathlib import Path

import pytest

from aiko_services_tpu.analysis import (
    ERROR, RULES, ModuleIndex, analyze_element_sources,
    analyze_framework, lint_definition, lint_paths, preflight)
from aiko_services_tpu.pipeline import (
    DefinitionError, Pipeline, parse_pipeline_definition)
from aiko_services_tpu.pipeline.definition import load_pipeline_definition

FIXTURES = Path(__file__).resolve().parent / "lint_fixtures"
REPO = Path(__file__).resolve().parents[1]

#: fixture file -> the ONE rule it must trigger (and nothing else).
DEFINITION_FIXTURES = {
    "bad_graph.json": "bad-graph",
    "unknown_element.json": "unknown-element",
    "unbound_input.json": "unbound-input",
    "dead_output.json": "dead-output",
    "key_collision.json": "key-collision",
    "bad_mapping.json": "bad-mapping",
    "fallback_mismatch.json": "fallback-mismatch",
    "unused_element.json": "unused-element",
    "bad_placement.json": "bad-placement",
    "bad_replicas.json": "bad-placement",
    "replicas_on_unplaced.json": "replicas-on-unplaced",
    "placement_remote.json": "placement-remote",
    "bad_parameter.json": "bad-parameter",
    "bad_element_parameter.json": "bad-parameter",
    "bad_prefix_cache.json": "bad-parameter",
    "bad_data_plane.json": "bad-parameter",
    "bad_qos.json": "bad-parameter",
    "bad_qos_tenant.json": "bad-parameter",
    "bad_journal.json": "bad-parameter",
    "bad_drain_timeout.json": "bad-parameter",
    "bad_slo.json": "bad-parameter",
    "bad_fleet.json": "bad-parameter",
    "bad_controller.json": "bad-parameter",
    "data_plane_on_local.json": "data-plane-on-local",
    "bad_source.py": "bad-source",
    "undeclared_host_input.json": "undeclared-host-input",
    "device_fn_host_call.json": "device-fn-host-call",
    "unread_parameter.json": "unread-parameter",
    "donation_alias.json": "donation-alias",
}

#: selfcheck fixture tree -> its rule (each tree carries a healthy
#: baseline -- matched hook pair, full span files -- plus ONE breakage).
SELFCHECK_FIXTURES = {
    "hook_parity": "hook-parity",
    "handler_liveness": "handler-liveness",
    "span_sync": "span-sync",
    "resume_identity": "resume-identity",
    "parameter_registry": "parameter-registry",
    "metric_registry": "metric-registry",
    "kernel_test": "kernel-test",
    "kernel_table": "kernel-table",
}


# -- fixture corpus: each rule fires exactly at its fixture -----------------

@pytest.mark.parametrize("filename,rule",
                         sorted(DEFINITION_FIXTURES.items()))
def test_definition_fixture_fires_exactly_its_rule(filename, rule):
    report = lint_paths([FIXTURES / filename])
    assert [f.rule for f in report.findings] == [rule], report.render()


@pytest.mark.parametrize("dirname,rule", sorted(SELFCHECK_FIXTURES.items()))
def test_selfcheck_fixture_fires_exactly_its_rule(dirname, rule):
    findings = analyze_framework(FIXTURES / "selfcheck" / dirname,
                                 registry={})
    assert [f.rule for f in findings] == [rule], \
        "\n".join(f.render() for f in findings)


def test_element_parameter_domains_scoped_to_module():
    """ELEMENT_PARAMETERS is keyed by (module, class): a user's
    unrelated class that happens to be named LLM never has the serving
    element's value domains imposed on it, while path-form references
    to the real module normalize and match."""
    from aiko_services_tpu.analysis.params import \
        validate_element_parameters

    assert validate_element_parameters(
        "LLM", {"speculative": "banana"}, "p: a",
        module="my_app.models") == []
    findings = validate_element_parameters(
        "LLM", {"speculative": "banana"}, "p: a",
        module="aiko_services_tpu/elements/llm.py")
    assert [f.rule for f in findings] == ["bad-parameter"]


def test_prefix_cache_knob_domains():
    """ISSUE 18 shared-prefix KV knobs validate at create time: each
    bad value fires exactly one bad-parameter finding, and the full
    good configuration (including ``speculative: auto``) is clean."""
    from aiko_services_tpu.analysis.params import \
        validate_element_parameters

    module = "aiko_services_tpu.elements.llm"
    assert validate_element_parameters(
        "LLM", {"prefix_cache": "on", "prefix_min_tokens": 64,
                "spec_autoprobe": "off", "speculative": "auto"},
        "p: a", module=module) == []
    for bad in ({"prefix_cache": "maybe"},
                {"prefix_min_tokens": 0},
                {"prefix_min_tokens": "lots"},
                {"spec_autoprobe": "sometimes"}):
        findings = validate_element_parameters(
            "LLM", bad, "p: a", module=module)
        assert [f.rule for f in findings] == ["bad-parameter"], bad


def test_every_rule_has_a_fixture():
    covered = set(DEFINITION_FIXTURES.values()) \
        | set(SELFCHECK_FIXTURES.values())
    assert covered == set(RULES)


def test_findings_carry_graph_path_context():
    report = lint_paths([FIXTURES / "unbound_input.json"])
    finding = report.findings[0]
    # pipeline name -> node path -> offending field
    assert "fx_unbound_input: a->b: b.input.nope" in finding.render()


# -- escape hatches ---------------------------------------------------------

def test_source_comment_disable_suppresses_rule():
    findings = analyze_element_sources([FIXTURES / "broken_elements.py"])
    by_rule = {}
    for finding in findings:
        by_rule.setdefault(finding.rule, []).append(finding)
    # the source-visible violations -- including the ones hidden
    # behind the module-local _as_uint8 wrapper and behind _via_import
    # (a local wrapper around elements/image.py's as_uint8); the
    # "# aiko-lint: disable=..." twin (SuppressedHostInput) is silent.
    assert sorted(by_rule) == ["device-fn-host-call",
                               "undeclared-host-input"]
    assert len(by_rule["device-fn-host-call"]) == 1
    assert len(by_rule["undeclared-host-input"]) == 3
    assert any("host-materializing helper" in f.message
               for f in by_rule["undeclared-host-input"])
    assert any("ImportWrappedHostInput" in f.message
               for f in by_rule["undeclared-host-input"])
    assert not any("SuppressedHostInput" in f.message for f in findings)


def test_missing_source_path_is_a_finding():
    report = lint_paths([FIXTURES / "no_such_file.py"])
    assert [f.rule for f in report.findings] == ["bad-source"]
    report = lint_paths([FIXTURES / "no_such_definition.json"])
    assert [f.rule for f in report.findings] == ["bad-source"]


def test_unknown_lint_key_rule_rejected():
    with pytest.raises(DefinitionError, match="dead_output"):
        parse_pipeline_definition({
            "version": 0, "name": "p_typo", "runtime": "jax",
            "graph": ["(a)"],
            "elements": [
                {"name": "a", "input": [], "output": [],
                 "lint": ["dead_output"],    # underscore typo
                 "deploy": {"local": {
                     "module": "tests/lint_fixtures/broken_elements.py",
                     "class_name": "CleanHead"}}}]})


def test_module_index_reparses_on_mtime_change(tmp_path):
    source = tmp_path / "elem.py"
    source.write_text(
        "import numpy as np\n"
        "from aiko_services_tpu.pipeline import PipelineElement\n"
        "class E(PipelineElement):\n"
        "    def process_frame(self, stream, image=None):\n"
        "        return True, {'n': np.asarray(image).size}\n")
    index = ModuleIndex()
    assert [f.rule for f in
            analyze_element_sources([source], index)] \
        == ["undeclared-host-input"]
    fixed = source.read_text().replace(
        "class E(PipelineElement):",
        "class E(PipelineElement):\n    host_inputs = ('image',)")
    source.write_text(fixed)
    os.utime(source, ns=(1, 1))             # force a distinct mtime
    assert not analyze_element_sources([source], index)


def test_fallback_signature_compares_by_name_not_order():
    # same names in a different declaration order binds identically at
    # runtime (**inputs / mappings are by name): no finding.
    module = "tests/lint_fixtures/broken_elements.py"
    definition = parse_pipeline_definition({
        "version": 0, "name": "p_fb_order", "runtime": "jax",
        "graph": ["(a r s)"],
        "elements": [
            {"name": "a", "input": [],
             "output": [{"name": "x"}, {"name": "y"}],
             "deploy": {"local": {"module": module,
                                  "class_name": "CleanHead"}}},
            {"name": "r", "input": [{"name": "x"}, {"name": "y"}],
             "output": [{"name": "out"}],
             "deploy": {"remote": {"name": "fx_worker"}},
             "fallback": "fb"},
            {"name": "fb", "input": [{"name": "y"}, {"name": "x"}],
             "output": [{"name": "out"}],
             "deploy": {"local": {"module": module,
                                  "class_name": "CleanHead"}}},
            {"name": "s", "input": [{"name": "out"}], "output": [],
             "deploy": {"local": {"module": module,
                                  "class_name": "CleanSink"}}}]})
    assert not lint_definition(definition).findings


def test_definition_lint_key_suppresses_rule():
    definition = load_pipeline_definition(
        str(FIXTURES / "unbound_input.json"))
    assert lint_definition(definition).findings
    definition.lint_disable = ("unbound-input",)    # JSON: "lint": [...]
    assert not lint_definition(definition).findings


def test_key_collision_fixture_exercises_element_lint_key():
    # b's "lint": ["dead-output"] suppresses the secondary finding (the
    # walk runs b after the join), leaving exactly the collision.
    definition = load_pipeline_definition(
        str(FIXTURES / "key_collision.json"))
    assert definition.element("b").lint_disable == ("dead-output",)
    rules = [f.rule for f in lint_definition(definition).findings]
    assert rules == ["key-collision"]


# -- in-tree cleanliness (the acceptance gate) ------------------------------

def test_examples_and_elements_lint_clean():
    paths = sorted((REPO / "examples").rglob("*.json"))
    assert paths, "no example definitions found"
    paths.append(REPO / "aiko_services_tpu" / "elements")
    report = lint_paths(paths)
    assert not report.findings, report.render()


def test_framework_self_check_clean():
    """``aiko_lint --self`` inside tier-1: hook parity, handler
    liveness, span sync, resume-post identity, parameter registry --
    all over the real package sources."""
    findings = analyze_framework()
    assert not findings, "\n".join(f.render() for f in findings)


def test_preflight_cost_is_create_time_cheap():
    """The e2e-style definition pre-flights in well under 100 ms once
    the module index is warm (bench records the cold number)."""
    definition = load_pipeline_definition(
        str(REPO / "examples" / "speech" / "pipeline_speech.json"))
    lint_definition(definition)                     # warm the AST cache
    start = time.perf_counter()
    report = lint_definition(definition)
    elapsed_ms = (time.perf_counter() - start) * 1000.0
    assert not report.findings, report.render()
    assert elapsed_ms < 100.0, f"pre-flight took {elapsed_ms:.1f} ms"


# -- Pipeline.__init__ pre-flight -------------------------------------------

def _broken_definition():
    return parse_pipeline_definition({
        "version": 0, "name": "p_preflight", "runtime": "jax",
        "graph": ["(a (c (v: ghost.x)))"],
        "elements": [
            {"name": "a", "input": [], "output": [{"name": "x"}],
             "deploy": {"local": {
                 "module": "tests/lint_fixtures/broken_elements.py",
                 "class_name": "CleanHead"}}},
            {"name": "c", "input": [{"name": "v"}, {"name": "x"}],
             "output": [],
             "deploy": {"local": {
                 "module": "tests/lint_fixtures/broken_elements.py",
                 "class_name": "CleanSink"}}}]})


def test_pipeline_create_rejects_error_findings(runtime):
    with pytest.raises(DefinitionError) as excinfo:
        Pipeline(_broken_definition(), runtime=runtime)
    message = str(excinfo.value)
    assert "pre-flight failed" in message
    assert "bad-mapping" in message
    assert "p_preflight: a->c" in message           # graph-path context


def test_pipeline_create_strict_rejects_warnings(runtime):
    definition = load_pipeline_definition(
        str(FIXTURES / "unbound_input.json"))
    Pipeline(definition, runtime=runtime)           # warning passes "on"
    with pytest.raises(DefinitionError, match="unbound-input"):
        Pipeline(definition, name="p_strict", runtime=runtime,
                 preflight="strict")


def test_pipeline_create_preflight_off_bypasses(runtime):
    definition = _broken_definition()
    definition.parameters["preflight"] = "off"
    Pipeline(definition, runtime=runtime)           # frame N's problem


def test_preflight_gate_severities():
    broken = _broken_definition()
    with pytest.raises(DefinitionError):
        preflight(broken)                           # error severity
    assert preflight(broken, mode="off") is None
    broken.parameters["preflight"] = "off"
    with pytest.raises(DefinitionError):
        preflight(broken, mode="strict")            # --check beats "off"
    warn_only = load_pipeline_definition(
        str(FIXTURES / "unbound_input.json"))
    report = preflight(warn_only)                   # warnings survive "on"
    assert [f.rule for f in report.findings] == ["unbound-input"]
    assert all(f.severity != ERROR for f in report.findings)
