"""Test configuration.

JAX runs on the CPU backend with 8 virtual devices so every sharding /
mesh / collective path is exercised without TPU hardware (the env vars must
be set before jax is first imported anywhere).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# A site hook (e.g. a TPU-tunnel PJRT plugin) may have imported jax at
# interpreter start and overridden jax_platforms programmatically, which
# wins over the env var; force it back before any backend initializes so
# tests never touch (or hang on) remote hardware.
import jax

jax.config.update("jax_platforms", "cpu")

import pytest


@pytest.fixture
def runtime():
    """Fresh isolated process runtime on the in-memory loopback broker."""
    from aiko_services_tpu.transport import reset_broker
    from aiko_services_tpu.runtime import init_process, reset_process
    from aiko_services_tpu.services.share import reset_services_cache

    reset_broker()
    reset_services_cache()
    rt = init_process(transport="loopback")
    rt.initialize()
    yield rt
    rt.engine.terminate()
    reset_process()
    reset_services_cache()
    reset_broker()


def run_until(rt, predicate, timeout=5.0):
    """Run the runtime's event loop until predicate() or timeout; returns
    predicate()'s final value."""
    rt.run(until=predicate, timeout=timeout)
    return predicate()
