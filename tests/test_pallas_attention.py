"""Pallas flash attention (interpret mode on CPU) == dense attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiko_services_tpu.ops import attention_prefill, repeat_kv
from aiko_services_tpu.ops.pallas_attention import flash_attention


def _dense(q, k, v, q_offset=0):
    b, s = q.shape[:2]
    positions = q_offset + jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    return attention_prefill(q, k, v, positions)


def test_flash_matches_dense():
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (2, 64, 4, 16))
    k = jax.random.normal(jax.random.fold_in(key, 1), (2, 64, 4, 16))
    v = jax.random.normal(jax.random.fold_in(key, 2), (2, 64, 4, 16))
    out = flash_attention(q, k, v, block_q=16, block_k=16)
    np.testing.assert_allclose(out, _dense(q, k, v), atol=1e-5)


def test_flash_gqa_index_map():
    """4 query heads over 2 KV heads -- no repeated KV materialization."""
    key = jax.random.PRNGKey(3)
    q = jax.random.normal(key, (2, 32, 4, 16))
    k = jax.random.normal(jax.random.fold_in(key, 1), (2, 32, 2, 16))
    v = jax.random.normal(jax.random.fold_in(key, 2), (2, 32, 2, 16))
    out = flash_attention(q, k, v, block_q=8, block_k=8)
    dense = _dense(q, repeat_kv(k, 2), repeat_kv(v, 2))
    np.testing.assert_allclose(out, dense, atol=1e-5)


def test_flash_ragged_lengths():
    """S and T not multiples of the block sizes (pad/mask path)."""
    key = jax.random.PRNGKey(4)
    q = jax.random.normal(key, (1, 37, 2, 16))
    k = jax.random.normal(jax.random.fold_in(key, 1), (1, 37, 2, 16))
    v = jax.random.normal(jax.random.fold_in(key, 2), (1, 37, 2, 16))
    out = flash_attention(q, k, v, block_q=16, block_k=16)
    np.testing.assert_allclose(out, _dense(q, k, v), atol=1e-5)


def test_flash_chunked_prefill_offset():
    """Queries begin at absolute position 24 against a 56-long KV."""
    key = jax.random.PRNGKey(5)
    q = jax.random.normal(key, (1, 32, 2, 16))
    k = jax.random.normal(jax.random.fold_in(key, 1), (1, 56, 2, 16))
    v = jax.random.normal(jax.random.fold_in(key, 2), (1, 56, 2, 16))
    out = flash_attention(q, k, v, q_offset=24, block_q=16, block_k=16)
    np.testing.assert_allclose(out, _dense(q, k, v, q_offset=24),
                               atol=1e-5)


def test_flash_non_causal():
    key = jax.random.PRNGKey(6)
    q = jax.random.normal(key, (1, 16, 2, 16))
    k = jax.random.normal(jax.random.fold_in(key, 1), (1, 16, 2, 16))
    v = jax.random.normal(jax.random.fold_in(key, 2), (1, 16, 2, 16))
    out = flash_attention(q, k, v, causal=False, block_q=8, block_k=8)
    scale = 16 ** -0.5
    logits = jnp.einsum("bshd,bthd->bhst", q, k) * scale
    dense = jnp.einsum("bhst,bthd->bshd", jax.nn.softmax(logits, -1), v)
    np.testing.assert_allclose(out, dense, atol=1e-5)


def test_flash_bfloat16():
    key = jax.random.PRNGKey(7)
    q = jax.random.normal(key, (2, 32, 4, 16), dtype=jnp.bfloat16)
    k = jax.random.normal(jax.random.fold_in(key, 1), (2, 32, 4, 16),
                          dtype=jnp.bfloat16)
    v = jax.random.normal(jax.random.fold_in(key, 2), (2, 32, 4, 16),
                          dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, block_q=16, block_k=16)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32),
        np.asarray(_dense(q, k, v), dtype=np.float32), atol=6e-2)


def test_flash_pack_heads_matches_unpacked():
    """Cross-head packing (two kv heads per grid row, block-diagonal
    queries over a 128-wide contraction) is numerically exact vs the
    unpacked kernel -- including chunked-prefill offsets, GQA groups,
    and ragged shapes.  (Measured on v5e it is slightly slower, so it
    is an option, not the default -- see the flash_attention
    docstring.)"""
    key = jax.random.PRNGKey(11)
    for (s, t, hkv, g, d, off) in ((64, 256, 4, 2, 64, 192),
                                   (48, 100, 2, 3, 32, 52),
                                   (128, 128, 6, 1, 64, 0)):
        q = jax.random.normal(key, (2, s, hkv * g, d))
        k = jax.random.normal(jax.random.fold_in(key, 1), (2, t, hkv, d))
        v = jax.random.normal(jax.random.fold_in(key, 2), (2, t, hkv, d))
        base = flash_attention(q, k, v, q_offset=off,
                               block_q=32, block_k=64)
        packed = flash_attention(q, k, v, q_offset=off,
                                 block_q=32, block_k=64,
                                 pack_heads=True)
        np.testing.assert_allclose(np.asarray(packed), np.asarray(base),
                                   atol=1e-5, rtol=1e-5)


def test_flash_pack_heads_falls_back_when_unpaired():
    """Odd kv-head counts / d > 64 silently use the unpacked path."""
    key = jax.random.PRNGKey(12)
    q = jax.random.normal(key, (1, 32, 3, 16))
    k = jax.random.normal(jax.random.fold_in(key, 1), (1, 32, 3, 16))
    v = jax.random.normal(jax.random.fold_in(key, 2), (1, 32, 3, 16))
    out = flash_attention(q, k, v, block_q=8, block_k=8, pack_heads=True)
    np.testing.assert_allclose(out, _dense(q, k, v), atol=1e-5)
