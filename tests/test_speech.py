"""Speech path (BASELINE config 5): ASR/TTS models and the end-to-end
WAV -> ASR -> LLM -> TTS pipeline on the loopback runtime (reference
equivalent: examples/speech/speech_elements.py WhisperX/Coqui chain)."""

import queue

import jax
import jax.numpy as jnp
import numpy as np

from conftest import run_until
from aiko_services_tpu.elements import write_wav
from aiko_services_tpu.models import asr as asr_model
from aiko_services_tpu.models import tts as tts_model
from aiko_services_tpu.pipeline import Pipeline
from test_media import definition, element


# -- ASR model --------------------------------------------------------------

def test_asr_transcribe_shapes_and_determinism():
    config = asr_model.AsrConfig.tiny()
    params = asr_model.init_params(jax.random.PRNGKey(0), config)
    chunk = int(config.sample_rate * config.chunk_seconds)
    audio = jax.random.normal(jax.random.PRNGKey(1), (2, chunk)) * 0.1
    tokens = asr_model.transcribe(params, config, audio)
    assert tokens.shape == (2, config.max_text)
    again = asr_model.transcribe(params, config, audio)
    np.testing.assert_array_equal(np.asarray(tokens), np.asarray(again))
    # decode_text round-trips token rows into a python string
    assert isinstance(asr_model.decode_text(config, tokens[0]), str)


def test_asr_loss_decreases_under_training():
    """Three SGD steps on one fabricated (audio, text) pair reduce the
    teacher-forced loss -- the model learns (the fitting objective
    works end to end through the mel frontend)."""
    config = asr_model.AsrConfig.tiny()
    params = asr_model.init_params(jax.random.PRNGKey(0), config)
    chunk = int(config.sample_rate * config.chunk_seconds)
    audio = jax.random.normal(jax.random.PRNGKey(1), (1, chunk)) * 0.1
    text = asr_model.encode_text(config, "hi") + [config.eos_token]
    targets = np.full((1, config.max_text), 259, dtype=np.int32)
    targets[0, :len(text)] = text
    targets = jnp.asarray(targets)

    grad_fn = jax.jit(jax.value_and_grad(
        lambda p: asr_model.asr_loss(p, config, audio, targets)))
    losses = []
    for _ in range(3):
        loss, grads = grad_fn(params)
        losses.append(float(loss))
        params = jax.tree.map(lambda p, g: p - 0.1 * g.astype(p.dtype),
                              params, grads)
    assert losses[-1] < losses[0]


def test_asr_partition_specs_cover_params():
    """Every parameter leaf has a partition spec (TP layout total)."""
    config = asr_model.AsrConfig.tiny()
    params = asr_model.init_params(jax.random.PRNGKey(0), config)
    specs = asr_model.partition_specs(config)
    flat_p = jax.tree_util.tree_flatten_with_path(params)[0]
    flat_s = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, tuple))[0]
    assert {jax.tree_util.keystr(k) for k, _ in flat_p} == \
           {jax.tree_util.keystr(k) for k, _ in flat_s}


# -- TTS model --------------------------------------------------------------

def test_tts_synthesize_waveform():
    config = tts_model.TtsConfig.tiny()
    params = tts_model.init_params(jax.random.PRNGKey(0), config)
    waveform = tts_model.synthesize(params, config, "aloha")
    assert waveform.shape == (config.n_frames * config.hop,)
    assert np.all(np.isfinite(waveform))
    assert np.max(np.abs(waveform)) <= 1.0 + 1e-5


def test_tts_loss_decreases_under_training():
    config = tts_model.TtsConfig.tiny()
    params = tts_model.init_params(jax.random.PRNGKey(0), config)
    tokens = jnp.asarray(tts_model.encode_text(config, "aloha"))[None]
    target = jax.random.normal(jax.random.PRNGKey(2),
                               (1, config.n_frames, config.n_mels))
    grad_fn = jax.jit(jax.value_and_grad(
        lambda p: tts_model.tts_loss(p, config, tokens, target)))
    losses = []
    for _ in range(3):
        loss, grads = grad_fn(params)
        losses.append(float(loss))
        params = jax.tree.map(lambda p, g: p - 0.1 * g.astype(p.dtype),
                              params, grads)
    assert losses[-1] < losses[0]


# -- end-to-end pipeline ----------------------------------------------------

def test_speech_pipeline_wav_to_reply_wav(tmp_path, runtime):
    """WAV in -> resample -> ASR -> LLM -> TTS -> WAV out: the full
    voice round trip of the reference's speech pipelines, single
    process, loopback fabric, tiny models."""
    source = tmp_path / "in.wav"
    target = tmp_path / "reply.wav"
    rng = np.random.default_rng(0)
    write_wav(source, rng.standard_normal(4000).astype(np.float32) * 0.1,
              8000)

    pipeline = Pipeline(definition(
        ["(Read Resample Asr Llm Tts Write)"],
        [element("Read", "AudioReadFile", ["path"],
                 ["audio", "sample_rate"],
                 {"data_sources": f"file://{source}"}),
         element("Resample", "AudioResampler", ["audio", "sample_rate"],
                 ["audio", "sample_rate"], {"target_rate": 16000}),
         element("Asr", "ASR", ["audio", "sample_rate"], ["text"],
                 {"model_size": "tiny"}),
         element("Llm", "LLM", ["text"], ["text"],
                 {"max_new_tokens": 4, "max_seq": 64}),
         element("Tts", "TTS", ["text"], ["audio", "sample_rate"],
                 {"model_size": "tiny"}),
         element("Write", "AudioWriteFile", ["audio", "sample_rate"],
                 ["path"], {"data_targets": f"file://{target}"})],
        name="p_speech"), runtime=runtime)

    responses = queue.Queue()
    pipeline.create_stream_local("s1", queue_response=responses)
    assert run_until(runtime, lambda: not responses.empty(), timeout=120.0)
    _, _, swag, _, okay, diagnostic = responses.get()
    assert okay, diagnostic
    assert target.exists()
    from aiko_services_tpu.elements import read_wav
    samples, rate = read_wav(target)
    assert rate == 16000
    assert len(samples) > 0


def test_asr_rejects_wrong_rate(runtime):
    """ASR errors (StreamEvent.ERROR -> diagnostic) on non-model-rate
    audio instead of silently mis-transcribing."""
    pipeline = Pipeline(definition(
        ["(Asr)"],
        [element("Asr", "ASR", ["audio", "sample_rate"], ["text"],
                 {"model_size": "tiny"})],
        name="p_asr_rate"), runtime=runtime)
    responses = queue.Queue()
    stream = pipeline.create_stream_local("s1", queue_response=responses)
    pipeline.create_frame_local(
        stream, {"audio": np.zeros(100, np.float32), "sample_rate": 8000})
    assert run_until(runtime, lambda: not responses.empty(), timeout=30.0)
    _, _, _, _, okay, diagnostic = responses.get()
    assert not okay
    assert "16000" in diagnostic


def test_streaming_asr_gated_speech_pipeline(runtime):
    """The config-5 streaming composition: audio hops -> streaming ASR
    (hop partials, endpoint finalization, the new ``utterance_end``
    output) -> TextFilter gate -> downstream stage.  Per-hop frames
    DROP at the gate; exactly the utterance-end frame passes."""
    import tests_media_helpers
    collected = []
    tests_media_helpers.SINK = collected

    pipeline = Pipeline(definition(
        ["(Asr (Gate (Collect)))"],
        [element("Asr", "ASR", ["audio", "sample_rate"],
                 ["text", "partial_text", "utterance_end"],
                 # tiny config has a 1.0 s chunk; 0.25 s hops keep the
                 # 0.75 s utterance BELOW chunk-fill so the silence
                 # hop's ENERGY ENDPOINT is the only finalizer -- the
                 # mechanism under test.
                 {"model_size": "tiny", "streaming": True,
                  "hop_seconds": 0.25, "endpoint_silence": 0.25}),
         element("Gate", "TextFilter", ["text", "utterance_end"],
                 ["text"], {"gate": "utterance_end"}),
         {"name": "Collect", "input": [{"name": "text"}], "output": [],
          "deploy": {"local": {"module": "tests_media_helpers",
                               "class_name": "CollectText"}},
          "parameters": {}}],
        name="p_speech_gate"), runtime=runtime)
    responses = queue.Queue()
    stream = pipeline.create_stream_local("s1", queue_response=responses)

    rate = 16000
    rng = np.random.default_rng(0)
    hop = int(rate * 0.25)
    speech = (rng.standard_normal(hop) * 0.3).astype(np.float32)
    silence = np.zeros(hop, dtype=np.float32)
    for samples in (speech, speech):
        pipeline.create_frame_local(stream, {"audio": samples,
                                             "sample_rate": rate})
    # Speech hops alone never finalize (0.5 s < the 1 s chunk).
    assert run_until(
        runtime,
        lambda: pipeline.graph.get_node("Asr").element._streamers
        .get("s1") is not None
        and pipeline.graph.get_node("Asr").element._streamers["s1"]
        .partial_decodes >= 1, timeout=120.0)
    assert len(collected) == 0
    pipeline.create_frame_local(stream, {"audio": silence,
                                         "sample_rate": rate})
    # The silence hop's endpoint finalizes; ITS frame reaches Collect.
    assert run_until(runtime, lambda: len(collected) >= 1, timeout=120.0)
    assert len(collected) == 1
    assert isinstance(collected[0], str)          # gated TEXT output
    streamer = pipeline.graph.get_node("Asr").element._streamers["s1"]
    assert streamer.chunks_transcribed == 1       # endpoint finalized
    assert len(streamer._pending) == 0            # buffer flushed


def test_text_filter_drops_empty_and_gates():
    from aiko_services_tpu.elements.text import TextFilter
    from aiko_services_tpu.pipeline import StreamEvent
    from aiko_services_tpu.pipeline.element import ElementContext

    class _FakePipeline:
        def current_stream(self):
            return None

        def get_pipeline_parameter(self, name, default=None):
            return default

    drop_empty = TextFilter(ElementContext("f", None, _FakePipeline(), {}))
    assert drop_empty.process_frame(None, text="  ")[0] \
        == StreamEvent.DROP_FRAME
    event, outputs = drop_empty.process_frame(None, text="hi")
    assert event == StreamEvent.OKAY and outputs["text"] == "hi"

    gated = TextFilter(ElementContext(
        "f", None, _FakePipeline(), {"gate": "utterance_end"}))
    assert gated.process_frame(None, text="hi", utterance_end=False)[0] \
        == StreamEvent.DROP_FRAME
    event, outputs = gated.process_frame(None, text="",
                                         utterance_end=True)
    assert event == StreamEvent.OKAY      # gate passes even empty text

    # gate: text reaches the named parameter, not **inputs
    gate_text = TextFilter(ElementContext(
        "f", None, _FakePipeline(), {"gate": "text"}))
    assert gate_text.process_frame(None, text="hi")[0] == StreamEvent.OKAY
    assert gate_text.process_frame(None, text=" ")[0] \
        == StreamEvent.DROP_FRAME

    # array-valued gates must not raise on truthiness
    gated_array = TextFilter(ElementContext(
        "f", None, _FakePipeline(), {"gate": "detections"}))
    event, _ = gated_array.process_frame(
        None, text="x", detections=np.zeros((3, 4)))
    assert event == StreamEvent.OKAY
    assert gated_array.process_frame(
        None, text="x", detections=np.zeros((0, 4)))[0] \
        == StreamEvent.DROP_FRAME
    # numpy SCALARS gate on their value, not their size
    assert gated_array.process_frame(
        None, text="x", detections=np.bool_(False))[0] \
        == StreamEvent.DROP_FRAME
    assert gated_array.process_frame(
        None, text="x", detections=np.int64(0))[0] \
        == StreamEvent.DROP_FRAME
    assert gated_array.process_frame(
        None, text="x", detections=np.bool_(True))[0] \
        == StreamEvent.OKAY
    # a typo'd/unwired gate surfaces as an ERROR, not a silent drop
    event, outputs = gated_array.process_frame(None, text="x")
    assert event == StreamEvent.ERROR
    assert "detections" in outputs["diagnostic"]
