"""Test PipelineElements loaded by module path (mirrors the reference's
tests/unit/test_pipeline_graph.py elements A/B/C and
examples/pipeline/elements.py PE_0..PE_4)."""

from aiko_services_tpu.pipeline import PipelineElement, StreamEvent


class ElementA(PipelineElement):
    """outputs a -> (a)"""

    def process_frame(self, stream, a):
        return StreamEvent.OKAY, {"a": int(a)}


class ElementB(PipelineElement):
    """input a (or mapped), output b = a + 1"""

    def process_frame(self, stream, a):
        return StreamEvent.OKAY, {"b": int(a) + 1}


class ElementC(PipelineElement):
    """input b (or mapped), output c = b * 2"""

    def process_frame(self, stream, b):
        return StreamEvent.OKAY, {"c": int(b) * 2}


class Doubler(PipelineElement):
    def process_frame(self, stream, x):
        return StreamEvent.OKAY, {"x": int(x) * 2}


class AddOne(PipelineElement):
    def process_frame(self, stream, x):
        return StreamEvent.OKAY, {"x": int(x) + 1}


class Failer(PipelineElement):
    def process_frame(self, stream, **inputs):
        return StreamEvent.ERROR, {"diagnostic": "deliberate failure"}


class Raiser(PipelineElement):
    def process_frame(self, stream, **inputs):
        raise RuntimeError("exploded")


class Counter(PipelineElement):
    """Increments n each visit -- loop body element."""

    def process_frame(self, stream, n=0):
        return StreamEvent.OKAY, {"n": int(n) + 1}


class Stopper(PipelineElement):
    def process_frame(self, stream, **inputs):
        return StreamEvent.STOP, {}
