"""Test PipelineElements loaded by module path (mirrors the reference's
tests/unit/test_pipeline_graph.py elements A/B/C and
examples/pipeline/elements.py PE_0..PE_4)."""

from aiko_services_tpu.pipeline import PipelineElement, StreamEvent
from aiko_services_tpu.pipeline.tensor import TPUElement


class ElementA(PipelineElement):
    """outputs a -> (a)"""

    def process_frame(self, stream, a):
        return StreamEvent.OKAY, {"a": int(a)}


class ElementB(PipelineElement):
    """input a (or mapped), output b = a + 1"""

    def process_frame(self, stream, a):
        return StreamEvent.OKAY, {"b": int(a) + 1}


class ElementC(PipelineElement):
    """input b (or mapped), output c = b * 2"""

    def process_frame(self, stream, b):
        return StreamEvent.OKAY, {"c": int(b) * 2}


class Doubler(PipelineElement):
    def process_frame(self, stream, x):
        return StreamEvent.OKAY, {"x": int(x) * 2}


class AddOne(PipelineElement):
    def process_frame(self, stream, x):
        return StreamEvent.OKAY, {"x": int(x) + 1}


class Failer(PipelineElement):
    def process_frame(self, stream, **inputs):
        return StreamEvent.ERROR, {"diagnostic": "deliberate failure"}


class Raiser(PipelineElement):
    def process_frame(self, stream, **inputs):
        raise RuntimeError("exploded")


class Counter(PipelineElement):
    """Increments n each visit -- loop body element."""

    def process_frame(self, stream, n=0):
        return StreamEvent.OKAY, {"n": int(n) + 1}


class Stopper(PipelineElement):
    def process_frame(self, stream, **inputs):
        return StreamEvent.STOP, {}


class TensorScale(TPUElement):
    """TPU element: x -> x * factor on the element's mesh, jit-cached."""

    def __init__(self, context):
        super().__init__(context)
        self._scale = self.jit(lambda x, f: x * f)

    def process_frame(self, stream, x):
        factor, _ = self.get_parameter("factor", 2.0)
        return StreamEvent.OKAY, {"x": self._scale(x, float(factor))}


class TensorSum(TPUElement):
    """Reduce x to a scalar jax array."""

    def __init__(self, context):
        super().__init__(context)
        self._sum = self.jit(lambda x: x.sum())

    def process_frame(self, stream, x):
        return StreamEvent.OKAY, {"total": self._sum(x)}
