"""The speech models LEARN (VERDICT r2 item 6): a tiny ASR fitted on a
synthetic tone corpus transcribes held-out audio exactly, the KV-cached
greedy decode is self-consistent with the teacher-forced decoder, and
streaming transcription emits per-chunk text with exactly one compiled
dispatch per chunk (bounded live latency)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from aiko_services_tpu.models import asr as asr_model

# 4 "words", each a pure tone; the fitted model maps tone -> letter.
TONES = {"a": 400.0, "b": 800.0, "c": 1600.0, "d": 3000.0}


def tone_chunk(config, freq: float, rng: np.random.Generator):
    """One chunk of a tone with random phase + noise (so held-out draws
    differ from training draws)."""
    t = np.arange(int(config.sample_rate * config.chunk_seconds),
                  dtype=np.float32) / config.sample_rate
    phase = rng.uniform(0, 2 * np.pi)
    wave = 0.5 * np.sin(2 * np.pi * freq * t + phase)
    return (wave + rng.normal(0, 0.01, wave.shape)).astype(np.float32)


def targets_for(config, letters):
    rows = np.full((len(letters), config.max_text), 259, dtype=np.int32)
    for i, letter in enumerate(letters):
        text = asr_model.encode_text(config, letter) + [config.eos_token]
        rows[i, :len(text)] = text
    return jnp.asarray(rows)


@pytest.fixture(scope="module")
def fitted_asr():
    """Train the tiny ASR on the tone corpus until it is exact on its
    training draws (fresh jitter every step, so 'exact' already means
    generalizing over phase/noise)."""
    config = dataclasses.replace(asr_model.AsrConfig.tiny(),
                                 dtype="float32")
    params = asr_model.init_params(jax.random.PRNGKey(0), config)
    rng = np.random.default_rng(7)
    letters = list(TONES)
    targets = targets_for(config, letters)
    optimizer = optax.adam(3e-3)
    opt_state = optimizer.init(params)

    @jax.jit
    def train_step(params, opt_state, audio):
        loss, grads = jax.value_and_grad(asr_model.asr_loss)(
            params, config, audio, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    def batch():
        return jnp.asarray(np.stack(
            [tone_chunk(config, TONES[letter], rng)
             for letter in letters]))

    loss = None
    for step in range(400):
        params, opt_state, loss = train_step(params, opt_state, batch())
        if step % 25 == 24:
            decoded = [asr_model.decode_text(config, row)
                       for row in np.asarray(asr_model.transcribe(
                           params, config, batch()))]
            if decoded == letters:
                break
    else:
        pytest.fail(f"tone ASR did not converge (loss {float(loss)})")
    return config, params


def test_fitted_asr_transcribes_heldout_exactly(fitted_asr):
    config, params = fitted_asr
    rng = np.random.default_rng(12345)          # unseen draws
    letters = ["c", "a", "d", "b", "a"]
    audio = jnp.asarray(np.stack(
        [tone_chunk(config, TONES[letter], rng) for letter in letters]))
    tokens = np.asarray(asr_model.transcribe(params, config, audio))
    decoded = [asr_model.decode_text(config, row) for row in tokens]
    assert decoded == letters


def test_cached_decode_consistent_with_teacher_forcing(fitted_asr):
    """The KV-cached greedy loop must make exactly the choices the
    teacher-forced decoder would make on its own output -- the
    correctness contract of the O(S) rewrite."""
    config, params = fitted_asr
    rng = np.random.default_rng(99)
    audio = jnp.asarray(np.stack(
        [tone_chunk(config, TONES["b"], rng)]))
    tokens = np.asarray(asr_model.transcribe(params, config, audio))[0]

    encoded = asr_model.encode(params, config,
                               asr_model.log_mel(config, audio))
    inputs = jnp.asarray(
        np.concatenate([[config.bos_token], tokens[:-1]])[None])
    logits = asr_model._decode_states(params, config, inputs, encoded)
    rechecked = np.asarray(jnp.argmax(logits[0], axis=-1))
    for position, token in enumerate(tokens):
        assert rechecked[position] == token, \
            f"divergence at {position}"
        if token == config.eos_token:
            break


def test_streaming_transcription(fitted_asr):
    """Live mode: mic-sized pushes emit text exactly at chunk
    boundaries; every chunk costs one dispatch of the one compiled
    transcribe program (no recompilation as the stream runs -- the
    bounded-latency property)."""
    config, params = fitted_asr
    rng = np.random.default_rng(31)
    streamer = asr_model.StreamingAsr(params, config)
    say = ["a", "d", "c"]
    audio = np.concatenate(
        [tone_chunk(config, TONES[letter], rng) for letter in say])

    pieces, text = np.array_split(audio, 10), ""
    for piece in pieces:
        text += streamer.push(piece)
    text += streamer.flush()
    assert text == "adc"
    assert streamer.chunks_transcribed == 3

    cache_before = asr_model.transcribe._cache_size()
    text2 = streamer.push(tone_chunk(config, TONES["b"], rng))
    assert text2 == "b"
    assert asr_model.transcribe._cache_size() == cache_before


def test_streaming_element_live_path(fitted_asr, runtime):
    """mic-style frames through the real pipeline: the ASR element in
    streaming mode emits chunk text as frames arrive."""
    import queue

    from aiko_services_tpu.pipeline import Pipeline

    config, params = fitted_asr
    rng = np.random.default_rng(17)
    definition = {
        "version": 0, "name": "asr_stream", "runtime": "jax",
        "graph": ["(ASR)"],
        "parameters": {},
        "elements": [{
            "name": "ASR",
            "input": [{"name": "audio"}, {"name": "sample_rate"}],
            "output": [{"name": "text"}],
            "deploy": {"local": {
                "module": "aiko_services_tpu.elements.speech",
                "class_name": "ASR"}},
            "parameters": {"streaming": True},
        }]}
    pipeline = Pipeline(definition, runtime=runtime)
    # Inject the fitted float32 model (the element would otherwise
    # init bfloat16 random weights).
    asr_element = pipeline.graph.get_node("ASR").element
    asr_element._params = params
    asr_element._config = config

    responses: "queue.Queue" = queue.Queue()
    collected = []

    def drain(target):
        while not responses.empty():
            *_, swag, _metrics, okay, _diag = responses.get()
            assert okay
            collected.append(swag["text"])
        return len(collected) >= target

    audio = np.concatenate(
        [tone_chunk(config, TONES[letter], rng) for letter in "ba"])
    for piece in np.array_split(audio, 4):
        pipeline.process_frame_local(
            {"audio": piece, "sample_rate": config.sample_rate},
            stream_id="live", queue_response=responses)
    runtime.run(until=lambda: drain(4), timeout=60.0)
    assert "".join(collected) == "ba"


def test_tts_fits_mel_targets():
    """The TTS model learns too (the other half of the speech-path
    proof): fitted on synthetic (text, mel) pairs, it reproduces each
    text's target mel far better than it reproduces the WRONG text's
    target -- the mapping is text-conditional, not memorized noise."""
    import optax

    from aiko_services_tpu.models import tts as tts_model

    config = tts_model.TtsConfig.tiny()
    params = tts_model.init_params(jax.random.PRNGKey(0), config)
    texts = ["aa", "bb", "cc", "dd"]
    tokens = jnp.asarray(np.stack(
        [tts_model.encode_text(config, text) for text in texts]))
    # Distinct smooth mel patterns per text (sinusoid gratings).
    frames, mels = config.n_frames, config.n_mels
    grid_f = np.arange(frames)[:, None] / frames
    grid_m = np.arange(mels)[None, :] / mels
    targets = jnp.asarray(np.stack(
        [np.sin(2 * np.pi * ((i + 1) * grid_f + i * grid_m))
         for i in range(len(texts))], dtype=np.float32))

    optimizer = optax.adam(3e-3)
    opt_state = optimizer.init(params)

    @jax.jit
    def train_step(params, opt_state):
        loss, grads = jax.value_and_grad(tts_model.tts_loss)(
            params, config, tokens, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    loss = None
    for _ in range(300):
        params, opt_state, loss = train_step(params, opt_state)
        if float(loss) < 0.08:
            break
    assert float(loss) < 0.15, f"TTS did not fit (loss {float(loss)})"

    mel = tts_model.synthesize_mel(params, config, tokens)
    own = np.abs(np.asarray(mel) - np.asarray(targets)).mean()
    crossed = np.abs(np.asarray(mel)
                     - np.asarray(targets)[::-1]).mean()
    assert own * 3 < crossed        # conditional on the text

    # And the full path still yields a bounded waveform.
    wave = tts_model.synthesize(params, config, "ab")
    assert np.isfinite(wave).all() and np.abs(wave).max() <= 1.0 + 1e-5


def test_subchunk_streaming_partial_latency(fitted_asr):
    """VERDICT r3 item 6: with hop_seconds set, a live hypothesis is
    produced every hop -- per-push latency is bounded by the HOP, not
    chunk_seconds -- and the finalized text still equals the whole-chunk
    decode exactly."""
    config, params = fitted_asr
    rng = np.random.default_rng(41)
    hop_seconds = config.chunk_seconds / 4
    streamer = asr_model.StreamingAsr(params, config,
                                      hop_seconds=hop_seconds)
    chunk_audio = tone_chunk(config, TONES["a"], rng)
    reference = asr_model.decode_text(
        config, np.asarray(asr_model.transcribe(
            params, config, jnp.asarray(chunk_audio[None])))[0])

    pieces = np.array_split(chunk_audio, 4)
    final = streamer.push(pieces[0])
    # A quarter-chunk push already produced a live hypothesis: the
    # first-word latency is one hop, not the 10x longer chunk.
    assert final == ""
    assert streamer.partial_decodes >= 1
    assert isinstance(streamer.partial_text, str)
    first_partial = streamer.partial_text

    final += streamer.push(pieces[1])
    # Two consecutive hypotheses over the same tone agree: the stable
    # prefix holds the agreed text.
    if streamer.partial_text == first_partial:
        assert streamer.stable_text == first_partial
    final += streamer.push(pieces[2])
    final += streamer.push(pieces[3])
    assert final == reference           # finalized == whole-chunk decode
    assert streamer.partial_text == ""  # partial state reset at finalize


def test_streaming_endpoint_finalizes_early(fitted_asr):
    """Energy endpointing: speech followed by trailing silence
    finalizes the utterance immediately -- no waiting for the chunk to
    fill."""
    config, params = fitted_asr
    rng = np.random.default_rng(43)
    chunk = int(config.sample_rate * config.chunk_seconds)
    streamer = asr_model.StreamingAsr(params, config,
                                      endpoint_silence=0.1,
                                      endpoint_threshold=0.05)
    speech = tone_chunk(config, TONES["b"], rng)[:int(chunk * 0.4)]
    silence = np.zeros(int(chunk * 0.15), dtype=np.float32)

    assert streamer.push(speech) == ""          # no endpoint yet
    text = streamer.push(silence)               # trailing quiet >= 0.1 s
    reference = asr_model.decode_text(
        config, np.asarray(asr_model.transcribe(
            params, config, jnp.asarray(asr_model.pad_audio(
                config, np.concatenate([speech, silence]))[None])))[0])
    assert text == reference and text != ""     # finalized early, exact
    assert len(streamer._pending) == 0          # utterance consumed
    # Pure silence afterwards never endpoints (no speech to finalize).
    assert streamer.push(np.zeros(chunk // 2, np.float32)) == ""
