"""Registrar outage must not eject healthy workers from a LifeCycleManager
fleet: the ServicesCache purge is not a death signal.  After the directory
returns, reconciliation prunes only workers that really disappeared."""

from conftest import run_until

from aiko_services_tpu.orchestration import LifeCycleManager, LifeCycleClient
from aiko_services_tpu.services import Registrar
from aiko_services_tpu.services.share import services_cache_singleton
from aiko_services_tpu.transport import get_broker


def test_fleet_survives_registrar_bounce(runtime):
    registrar = Registrar(runtime=runtime, primary_search_timeout=0.05)
    clients = {}

    def launcher(cid, topic):
        clients[cid] = LifeCycleClient(f"w{cid}", cid, topic,
                                       runtime=runtime)

    removed = []
    manager = LifeCycleManager(
        launcher=launcher, runtime=runtime,
        client_change_handler=lambda ev, cid: removed.append((ev, cid)))
    manager.create_clients(2)
    assert run_until(runtime, lambda: manager.client_count() == 2,
                     timeout=5.0)
    cache = services_cache_singleton(runtime)
    assert run_until(
        runtime,
        lambda: all(cache.registry.get(c.topic_path) for c in
                    clients.values()),
        timeout=5.0)

    # Bounce: someone clobbers the retained election topic with "absent".
    # Every process sees the registrar vanish (cache purges); the primary
    # then re-asserts its retained "found" record and the directory
    # repopulates.
    get_broker().publish(runtime.topic_registrar_boot, "(primary absent)",
                         retain=True)
    assert run_until(runtime,
                     lambda: registrar.state == "primary"
                     and cache.state == "ready"
                     and runtime.registrar is not None,
                     timeout=5.0)
    # Fleet intact: no spurious removals, both workers still tracked.
    runtime.run(timeout=1.0)          # let reconciliation run
    assert manager.client_count() == 2
    assert not any(ev == "remove" for ev, _ in removed)
    manager.stop()
