"""Live media endpoints: mic:// capture, speaker:// playback, rtsp://
network-camera ingest -- driven by injected fake backends (the hardware
backends, sounddevice / cv2-FFMPEG, are module hooks; reference
audio_io.py:412-564, gstreamer/scheme_rtsp.py:27)."""

import queue

import numpy as np

from conftest import run_until
from aiko_services_tpu.elements import audio_live, scheme_rtsp
from aiko_services_tpu.pipeline import Pipeline
from test_media import definition, element


class FakeMicBackend:
    """Yields ``blocks`` then reports silence forever."""
    instances: list = []

    def __init__(self, device, sample_rate, block_samples, channels=1):
        self.device = device
        self.sample_rate = sample_rate
        self.blocks = queue.Queue()
        for i in range(3):
            self.blocks.put_nowait(
                np.full((block_samples, channels), 0.1 * (i + 1),
                        dtype=np.float32))
        self.closed = False
        FakeMicBackend.instances.append(self)

    def read(self, timeout=0.0):
        try:
            return self.blocks.get_nowait()
        except queue.Empty:
            return None

    def close(self):
        self.closed = True


class FakeSpeakerBackend:
    instances: list = []

    def __init__(self, device, sample_rate, channels=1):
        self.written = []
        self.closed = False
        FakeSpeakerBackend.instances.append(self)

    def write(self, samples):
        self.written.append(np.array(samples))

    def close(self):
        self.closed = True


class FakeCapture:
    """Three frames then end-of-stream."""
    instances: list = []

    def __init__(self, url):
        self.url = url
        self.remaining = 3
        self.released = False
        FakeCapture.instances.append(self)

    def isOpened(self):
        return True

    def read(self):
        if self.remaining <= 0:
            return False, None
        self.remaining -= 1
        frame = np.zeros((8, 8, 3), dtype=np.uint8)
        frame[:, :, 0] = 255              # BGR: blue channel saturated
        return True, frame

    def release(self):
        self.released = True


def test_microphone_to_speaker_pipeline(runtime, monkeypatch):
    """mic:// blocks flow through the pipeline into speaker:// playback;
    both backends open and close around the stream."""
    monkeypatch.setattr(audio_live, "input_backend_factory",
                        FakeMicBackend)
    monkeypatch.setattr(audio_live, "output_backend_factory",
                        FakeSpeakerBackend)
    FakeMicBackend.instances.clear()
    FakeSpeakerBackend.instances.clear()

    pipeline = Pipeline(definition(
        ["(Mic Play)"],
        [element("Mic", "MicrophoneRead", [], ["audio", "sample_rate"],
                 {"data_sources": "mic://default", "sample_rate": 8000,
                  "block_samples": 160}),
         element("Play", "SpeakerWrite", ["audio"], [],
                 {"data_targets": "speaker://default",
                  "sample_rate": 8000})],
        name="p_mic"), runtime=runtime)
    pipeline.create_stream_local("s1")
    assert run_until(
        runtime,
        lambda: FakeSpeakerBackend.instances
        and len(FakeSpeakerBackend.instances[0].written) >= 3,
        timeout=15.0)

    mic = FakeMicBackend.instances[0]
    speaker = FakeSpeakerBackend.instances[0]
    assert mic.sample_rate == 8000
    np.testing.assert_allclose(speaker.written[0], 0.1, rtol=1e-6)
    np.testing.assert_allclose(speaker.written[2], 0.3, rtol=1e-6)

    pipeline.destroy_stream("s1")
    assert run_until(runtime, lambda: mic.closed and speaker.closed,
                     timeout=10.0)


def test_microphone_open_failure_is_stream_error(runtime, monkeypatch):
    def broken_factory(*args, **kwargs):
        raise OSError("no such device")

    monkeypatch.setattr(audio_live, "input_backend_factory",
                        broken_factory)
    pipeline = Pipeline(definition(
        ["(Mic)"],
        [element("Mic", "MicrophoneRead", [], ["audio"],
                 {"data_sources": "mic://nope"})],
        name="p_mic_err"), runtime=runtime)
    # start_stream ERROR -> stream rejected synchronously (engine
    # contract: create_stream_local returns None, stream not registered).
    stream = pipeline.create_stream_local("s1")
    assert stream is None
    assert "s1" not in pipeline.streams


def test_speaker_rejects_rate_mismatch(runtime, monkeypatch):
    """Audio at a different rate than the opened device errors instead
    of silently playing at the wrong speed."""
    monkeypatch.setattr(audio_live, "output_backend_factory",
                        FakeSpeakerBackend)
    FakeSpeakerBackend.instances.clear()

    pipeline = Pipeline(definition(
        ["(Play)"],
        [element("Play", "SpeakerWrite", ["audio", "sample_rate"], [],
                 {"data_targets": "speaker://default",
                  "sample_rate": 16000})],
        name="p_spk_rate"), runtime=runtime)
    responses = queue.Queue()
    stream = pipeline.create_stream_local("s1", queue_response=responses)
    pipeline.create_frame_local(
        stream, {"audio": np.zeros(100, np.float32),
                 "sample_rate": 48000})
    assert run_until(runtime, lambda: not responses.empty(), timeout=10.0)
    _, _, _, _, okay, diagnostic = responses.get()
    assert not okay
    assert "48000" in diagnostic


def test_rtsp_rejects_multiple_urls(runtime, monkeypatch):
    monkeypatch.setattr(scheme_rtsp, "capture_factory", FakeCapture)
    pipeline = Pipeline(definition(
        ["(Rtsp)"],
        [element("Rtsp", "VideoReadRTSP", [], ["image"],
                 {"data_sources": ["rtsp://cam1/s", "rtsp://cam2/s"]})],
        name="p_rtsp_multi"), runtime=runtime)
    assert pipeline.create_stream_local("s1") is None


def test_rtsp_source_decodes_frames(runtime, monkeypatch):
    """rtsp:// frames arrive as RGB images; capture released at stop."""
    monkeypatch.setattr(scheme_rtsp, "capture_factory", FakeCapture)
    FakeCapture.instances.clear()

    import tests_media_helpers
    collected = tests_media_helpers.SINK = []

    pipeline = Pipeline(definition(
        ["(Rtsp Grab)"],
        [element("Rtsp", "VideoReadRTSP", [], ["image"],
                 {"data_sources": "rtsp://camera.local/stream1"}),
         {"name": "Grab", "input": [{"name": "image"}], "output": [],
          "deploy": {"local": {"module": "tests_media_helpers",
                               "class_name": "Collect"}},
          "parameters": {}}],
        name="p_rtsp"), runtime=runtime)
    pipeline.create_stream_local("s1")
    assert run_until(runtime, lambda: len(collected) >= 3, timeout=15.0)

    capture = FakeCapture.instances[0]
    assert capture.url == "rtsp://camera.local/stream1"
    first = np.asarray(collected[0])
    assert first.shape == (8, 8, 3)
    assert first[0, 0, 2] == 255          # BGR -> RGB flip happened
    assert first[0, 0, 0] == 0
    # End-of-stream (3 frames) stops the stream and releases capture.
    assert run_until(runtime, lambda: capture.released, timeout=10.0)

def test_rtsp_release_does_not_block_on_stalled_read():
    """A stalled network read must not park release() (the engine
    thread): release signals, returns fast, and the reader performs the
    native release when the read finally returns."""
    import threading
    import time

    from aiko_services_tpu.elements.scheme_rtsp import _CaptureGuard

    release_gate = threading.Event()

    class StalledCapture:
        def __init__(self):
            self.released = False

        def read(self):
            release_gate.wait(timeout=10.0)       # "network stall"
            return True, np.zeros((2, 2, 3), np.uint8)

        def release(self):
            self.released = True

    capture = StalledCapture()
    guard = _CaptureGuard(capture)
    results = []
    reader = threading.Thread(target=lambda: results.append(guard.read()))
    reader.start()
    time.sleep(0.05)                              # reader inside read()

    start = time.perf_counter()
    guard.release(timeout=0.2)
    elapsed = time.perf_counter() - start
    assert elapsed < 2.0                          # returned promptly
    assert not capture.released                   # deferred to reader

    release_gate.set()                            # stall ends
    reader.join(timeout=5.0)
    assert results == [(False, None)]             # read reports EOS
    assert capture.released                       # reader closed natively


def test_playback_pump_keeps_engine_unblocked():
    """SpeakerWrite playback goes through a writer thread: enqueueing is
    fast even when the backend write is real-time slow, and close drains."""
    import time

    from aiko_services_tpu.elements.audio_live import _PlaybackPump

    class SlowBackend:
        def __init__(self):
            self.written = []
            self.closed = False

        def write(self, samples):
            time.sleep(0.05)                      # "real-time" playback
            self.written.append(np.array(samples))

        def close(self):
            self.closed = True

    backend = SlowBackend()
    pump = _PlaybackPump(backend, queue_depth=8)
    start = time.perf_counter()
    for i in range(5):
        pump.write(np.full(10, i, np.float32))
    enqueue_time = time.perf_counter() - start
    assert enqueue_time < 0.1                     # engine never waited
    pump.close()
    assert backend.closed
    assert len(backend.written) >= 1              # playback happened


def test_playback_pump_backlog_raises():
    import time

    class StuckBackend:
        def write(self, samples):
            time.sleep(10.0)

        def close(self):
            pass

    from aiko_services_tpu.elements.audio_live import _PlaybackPump
    pump = _PlaybackPump(StuckBackend(), queue_depth=1)
    pump.write(np.zeros(4, np.float32))           # consumed by thread
    pump.write(np.zeros(4, np.float32), timeout=0.05)   # fills queue
    try:
        pump.write(np.zeros(4, np.float32), timeout=0.05)
        raised = False
    except RuntimeError as error:
        raised = True
        assert "backlog" in str(error)
    assert raised


# -- rtsp:// output (reference video_stream_writer.py:26) -------------------

class FakeWriter:
    """Records published frames in place of the ffmpeg subprocess."""
    instances: list = []

    def __init__(self, url, width, height, fps):
        self.url, self.width, self.height, self.fps = (url, width,
                                                       height, fps)
        self.frames: list = []
        self.closed = False
        FakeWriter.instances.append(self)

    def write(self, frame):
        self.frames.append(np.array(frame))

    def close(self):
        self.closed = True


def test_rtsp_target_publishes_frames(runtime, monkeypatch):
    """VideoWriteRTSP opens the writer lazily with the first frame's
    geometry, publishes every frame as uint8 RGB, passes images
    through, and closes the writer at stream stop."""
    monkeypatch.setattr(scheme_rtsp, "writer_factory", FakeWriter)
    FakeWriter.instances.clear()

    pipeline = Pipeline(definition(
        ["(Out)"],
        [element("Out", "VideoWriteRTSP", ["image"], ["image"],
                 {"data_targets": "rtsp://server.local/live",
                  "rate": 15})],
        name="p_rtsp_out"), runtime=runtime)
    responses = queue.Queue()
    stream = pipeline.create_stream_local("s1", queue_response=responses)
    for i in range(3):
        pipeline.create_frame_local(
            stream, {"image": np.full((4, 6, 3), 0.25 * (i + 1),
                                      np.float32)})
    done = []

    def drain():
        while not responses.empty():
            *_, okay, _diag = responses.get()
            done.append(okay)
        return len(done) >= 3
    assert run_until(runtime, drain, timeout=15.0)
    assert all(done)

    writer = FakeWriter.instances[0]
    assert (writer.url, writer.width, writer.height, writer.fps) \
        == ("rtsp://server.local/live", 6, 4, 15.0)
    # Writes drain on the pump thread (engine never blocks on the
    # encoder pipe) -- wait for the async drain.
    assert run_until(runtime, lambda: len(writer.frames) >= 3,
                     timeout=10.0)
    assert writer.frames[0].dtype == np.uint8
    assert int(writer.frames[0][0, 0, 0]) == 63        # 0.25 * 255
    assert not writer.closed

    pipeline.destroy_stream("s1")
    assert run_until(runtime, lambda: writer.closed, timeout=10.0)


def test_rtsp_target_write_failure_errors_frame(runtime, monkeypatch):
    """A dead publisher (broken pipe on the pump thread) surfaces as a
    frame ERROR on a subsequent frame, never a crash or an engine
    stall."""
    class BrokenWriter(FakeWriter):
        def write(self, frame):
            raise BrokenPipeError("encoder died")

    monkeypatch.setattr(scheme_rtsp, "writer_factory", BrokenWriter)
    FakeWriter.instances.clear()
    pipeline = Pipeline(definition(
        ["(Out)"],
        [element("Out", "VideoWriteRTSP", ["image"], ["image"],
                 {"data_targets": "rtsp://server.local/live"})],
        name="p_rtsp_broken"), runtime=runtime)
    responses = queue.Queue()
    stream = pipeline.create_stream_local("s1", queue_response=responses)
    failures = []

    def push_and_check():
        pipeline.create_frame_local(
            stream, {"image": np.zeros((2, 2, 3), np.uint8)})
        while not responses.empty():
            *_, okay, diagnostic = responses.get()
            if not okay:
                failures.append(diagnostic)
        return bool(failures)

    assert run_until(runtime, push_and_check, timeout=15.0)
    assert "rtsp publish failed" in failures[0]


def test_rtsp_target_rejects_geometry_change(runtime, monkeypatch):
    """The encoder is told the frame size once; a mid-stream resolution
    change must ERROR the frame, not silently misframe the video."""
    monkeypatch.setattr(scheme_rtsp, "writer_factory", FakeWriter)
    FakeWriter.instances.clear()
    pipeline = Pipeline(definition(
        ["(Out)"],
        [element("Out", "VideoWriteRTSP", ["image"], ["image"],
                 {"data_targets": "rtsp://server.local/live"})],
        name="p_rtsp_geom"), runtime=runtime)
    responses = queue.Queue()
    stream = pipeline.create_stream_local("s1", queue_response=responses)
    pipeline.create_frame_local(
        stream, {"image": np.zeros((4, 4, 3), np.uint8)})
    pipeline.create_frame_local(
        stream, {"image": np.zeros((8, 8, 3), np.uint8)})
    results = []

    def drain():
        while not responses.empty():
            *_, okay, diagnostic = responses.get()
            results.append((okay, diagnostic))
        return len(results) >= 2
    assert run_until(runtime, drain, timeout=10.0)
    assert results[0][0]
    assert not results[1][0]
    assert "geometry changed" in results[1][1]
