"""Element library tests: file scheme, text elements end-to-end (the
BASELINE config-1 smoke pipeline), expression and observe elements."""

import os

from conftest import run_until
from aiko_services_tpu.pipeline import Pipeline, StreamEvent


LIB = "aiko_services_tpu.elements.text"


def lib_element(name, cls, inputs, outputs, parameters=None, module=LIB):
    return {"name": name,
            "input": [{"name": n} for n in inputs],
            "output": [{"name": n} for n in outputs],
            "deploy": {"local": {"module": module, "class_name": cls}},
            "parameters": parameters or {}}


def test_text_pipeline_end_to_end(runtime, tmp_path):
    """file -> read -> upper -> write: the config-1 smoke pipeline."""
    source = tmp_path / "in_0.txt"
    source.write_text("hello tpu pipeline")
    target = tmp_path / "out.txt"

    p = Pipeline({
        "version": 0, "name": "p_text", "runtime": "jax",
        "graph": ["(READ XFORM WRITE)"],
        "elements": [
            lib_element("READ", "TextReadFile", ["path"], ["text"],
                        {"data_sources": f"file://{source}"}),
            lib_element("XFORM", "TextTransform", ["text"], ["text"],
                        {"transform": "upper"}),
            lib_element("WRITE", "TextWriteFile", ["text"], ["path"],
                        {"data_targets": f"file://{target}"}),
        ]}, runtime=runtime)

    p.post_self("create_stream", ["s1"])
    run_until(runtime, lambda: target.exists()
              and "HELLO TPU PIPELINE" in target.read_text(), timeout=5.0)
    assert "HELLO TPU PIPELINE" in target.read_text()


def test_text_pipeline_multi_file_generator(runtime, tmp_path):
    """Glob source -> one frame per file via the generator thread."""
    for i in range(3):
        (tmp_path / f"part_{i}.txt").write_text(f"chunk {i}")
    target = tmp_path / "merged" / "out_{}.txt"

    p = Pipeline({
        "version": 0, "name": "p_glob", "runtime": "jax",
        "graph": ["(READ WRITE)"],
        "elements": [
            lib_element("READ", "TextReadFile", ["path"], ["text"],
                        {"data_sources": f"file://{tmp_path}/part_{{}}.txt"}),
            lib_element("WRITE", "TextWriteFile", ["text"], ["path"],
                        {"data_targets": f"file://{target}"}),
        ]}, runtime=runtime)

    p.post_self("create_stream", ["s1"])
    out_dir = tmp_path / "merged"
    run_until(runtime,
              lambda: out_dir.exists() and len(os.listdir(out_dir)) >= 3,
              timeout=5.0)
    outputs = sorted(os.listdir(out_dir))
    assert len(outputs) == 3
    assert (out_dir / "out_0.txt").read_text().strip() == "chunk 0"
    assert (out_dir / "out_2.txt").read_text().strip() == "chunk 2"


def test_expression_element(runtime):
    p = Pipeline({
        "version": 0, "name": "p_expr", "runtime": "jax",
        "graph": ["(E)"],
        "elements": [
            lib_element("E", "Expression", [], [],
                        {"expressions": "total = a + b; flag = total > 10"},
                        module="aiko_services_tpu.elements.expression"),
        ]}, runtime=runtime)
    import queue
    responses = queue.Queue()
    p.process_frame_local({"a": 7, "b": 8}, queue_response=responses)
    run_until(runtime, lambda: not responses.empty(), timeout=5.0)
    _, _, swag, _, okay, _ = responses.get()
    assert okay and swag["total"] == 15 and swag["flag"] is True


def test_sample_element_drops_frames(runtime):
    import queue
    p = Pipeline({
        "version": 0, "name": "p_sample", "runtime": "jax",
        "graph": ["(S)"],
        "elements": [
            lib_element("S", "TextSample", ["text"], ["text"],
                        {"sample_rate": 2})]}, runtime=runtime)
    responses = queue.Queue()
    stream = None
    for i in range(4):
        p.process_frame_local({"text": f"t{i}"}, stream_id="s",
                              queue_response=responses)
    run_until(runtime, lambda: responses.qsize() >= 2, timeout=5.0)
    texts = []
    while not responses.empty():
        texts.append(responses.get()[2]["text"])
    assert texts == ["t0", "t2"]