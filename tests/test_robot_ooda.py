"""OODA robot example family (reference examples/robot/ooda/
elements.py:36-197, xgo_robot/xgo_robot.py:110-221): agentic pipeline
driving a discovered robot Actor over the fabric."""

import importlib.util
import pathlib
import queue
import sys

from conftest import run_until
from aiko_services_tpu.pipeline import create_pipeline

ROBOT_DIR = pathlib.Path(__file__).resolve().parent.parent \
    / "examples" / "robot"


def load_robot_actor():
    spec = importlib.util.spec_from_file_location(
        "robot_actor_test", ROBOT_DIR / "robot_actor.py")
    module = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = module
    spec.loader.exec_module(module)
    return module


def build(runtime):
    from aiko_services_tpu.services import Registrar

    Registrar(runtime=runtime, primary_search_timeout=0.05)
    module = load_robot_actor()
    robot = module.VirtualRobot(runtime=runtime)
    pipeline = create_pipeline(str(ROBOT_DIR / "robot_pipeline.json"),
                               runtime=runtime)
    responses = queue.Queue()
    stream = pipeline.create_stream_local("1", queue_response=responses)
    assert run_until(
        runtime,
        lambda: stream.variables.get("robot_proxy") is not None,
        timeout=10.0), "robot never discovered"
    return robot, pipeline, stream, responses


def test_commands_drive_discovered_robot(runtime):
    robot, pipeline, stream, responses = build(runtime)
    pipeline.create_frame_local(stream, {
        "texts": ["(forwards)", "(turn left)", "(hand close)", "(sit)"],
        "detections": [{"class": "octopus"}]})
    assert run_until(runtime,
                     lambda: robot.share["last_action"] == "sit",
                     timeout=10.0)
    assert robot.share["x"] == 10.0          # one stride before the turn
    assert robot.share["heading"] == 40.0
    assert robot.share["claw"] == 255
    _, _, swag, _, okay, _ = responses.get()
    assert okay
    assert [status for _, status in swag["actions"]] == ["ok"] * 4
    assert swag["Fusion.detections"] == ["octopus"]


def test_unknown_and_aliased_commands(runtime):
    robot, pipeline, stream, responses = build(runtime)
    pipeline.create_frame_local(stream, {
        "texts": ["(moonwalk)", "r"], "detections": []})
    assert run_until(runtime, lambda: not responses.empty(), timeout=10.0)
    _, _, swag, _, okay, _ = responses.get()
    assert okay
    assert dict(swag["actions"])["(moonwalk)"] == "unknown"
    assert dict(swag["actions"])["r"] == "ok"     # alias -> (reset)


def test_no_robot_yet_reports_status(runtime):
    """Commands before discovery degrade to no-robot, not a crash."""
    pipeline = create_pipeline(str(ROBOT_DIR / "robot_pipeline.json"),
                               runtime=runtime)
    responses = queue.Queue()
    stream = pipeline.create_stream_local("1", queue_response=responses)
    pipeline.create_frame_local(stream, {"texts": ["(forwards)"],
                                         "detections": []})
    assert run_until(runtime, lambda: not responses.empty(), timeout=10.0)
    _, _, swag, _, okay, _ = responses.get()
    assert okay
    assert swag["actions"] == [("(forwards)", "no-robot")]


def test_fusion_memory_decays(runtime):
    robot, pipeline, stream, responses = build(runtime)
    pipeline.create_frame_local(stream, {
        "texts": [], "detections": [{"class": "oak_tree"}]})
    for _ in range(9):                    # DETECTION_MEMORY = 8
        pipeline.create_frame_local(stream, {"texts": [],
                                             "detections": []})
    assert run_until(runtime, lambda: responses.qsize() >= 10,
                     timeout=10.0)
    views = []
    while not responses.empty():
        _, _, swag, _, _, _ = responses.get()
        views.append(swag["Fusion.detections"])
    assert views[0] == ["oak_tree"]
    assert views[7] == ["oak_tree"]       # still remembered
    assert views[8] == []                 # decayed after 8 frames


# -- hardware XGO actor (reference xgo_robot.py:110-221) --------------------

class MockXgoBackend:
    """Records the serial-command traffic the actor would send."""

    def __init__(self):
        self.calls = []
        self.battery = 87

    def __getattr__(self, name):
        def record(*args):
            self.calls.append((name,) + args)
        return record

    def read_battery(self):
        return self.battery

    def read_firmware(self):
        return "v1.2.3"


def load_xgo_module():
    spec = importlib.util.spec_from_file_location(
        "xgo_robot_test", ROBOT_DIR / "xgo_robot.py")
    module = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = module
    spec.loader.exec_module(module)
    return module


def test_xgo_actor_commands_reach_serial_backend(runtime):
    """Remote command calls land on the injected serial backend with
    the reference's range clamps applied."""
    from aiko_services_tpu.services import Registrar, get_service_proxy

    Registrar(runtime=runtime, primary_search_timeout=0.05)
    module = load_xgo_module()
    backend = MockXgoBackend()
    robot = module.XGORobot(runtime=runtime, backend=backend)
    assert robot.share["version_firmware"] == "v1.2.3"

    proxy = get_service_proxy(runtime, robot.topic_path)
    proxy.arm(200, -200)              # out of range both axes
    proxy.claw(300)
    proxy.move("x", 99)
    proxy.turn(-250)
    proxy.attitude(5, "nil", 99)
    proxy.action("sit")
    proxy.action("backflip")          # unknown: must NOT reach serial
    assert run_until(
        runtime, lambda: ("action", 12) in backend.calls,
        timeout=10.0)
    assert ("arm", 155, -95) in backend.calls          # clamped
    assert ("claw", 255) in backend.calls
    assert ("move", "x", 25) in backend.calls
    assert ("turn", -100) in backend.calls
    # xgolib serial contract: single-letter attitude directions and
    # numeric action ids ("sit" = 12).
    assert ("attitude", "p", 5) in backend.calls
    assert ("attitude", "y", 11) in backend.calls
    assert not any(call[0] == "action" and call[1] != 12
                   for call in backend.calls)
    assert run_until(
        runtime, lambda: robot.share.get("last_action") == "sit",
        timeout=10.0)

    robot._battery_monitor()          # timer body (period is 10 s)
    assert run_until(
        runtime, lambda: robot.share.get("battery") == 87, timeout=10.0)
