"""Recorder (namespace log aggregation) and Storage (sqlite actor) over
the loopback fabric."""

from conftest import run_until

from aiko_services_tpu.services import (
    Actor, Recorder, Registrar, ServiceFilter, Storage, do_request,
    get_service_proxy)


class Chatty(Actor):
    def __init__(self, name, runtime=None):
        super().__init__(name, "test/chatty:0", runtime=runtime)

    def say(self, text):
        self.logger.info(text)


def test_recorder_aggregates_logs(runtime):
    recorder = Recorder(runtime=runtime)
    chatty = Chatty("chatty", runtime=runtime)
    for i in range(5):
        chatty.say(f"line {i}")
    assert run_until(runtime,
                     lambda: chatty.topic_path in recorder.sources(),
                     timeout=5.0)
    tail = recorder.tail(chatty.topic_path)
    assert len(tail) == 5
    assert "line 4" in tail[-1]
    recorder.stop()


def test_recorder_replay_request(runtime):
    Registrar(runtime=runtime, primary_search_timeout=0.05)
    recorder = Recorder(runtime=runtime)
    chatty = Chatty("chatty2", runtime=runtime)
    chatty.say("hello recorder")
    run_until(runtime, lambda: chatty.topic_path in recorder.sources())

    results = []
    do_request(runtime, None, ServiceFilter(protocol="recorder"),
               lambda proxy, topic: proxy.replay(topic, chatty.topic_path,
                                                 8),
               lambda items: results.append(items))
    assert run_until(runtime, lambda: bool(results), timeout=5.0)
    lines = [parameters[0] for command, parameters in results[0]
             if command == "line"]
    assert any("hello recorder" in line for line in lines)
    recorder.stop()


def test_storage_roundtrip(runtime, tmp_path):
    storage = Storage(database_path=str(tmp_path / "kv.db"),
                      runtime=runtime)
    proxy = get_service_proxy(runtime, storage.topic_path)
    proxy.store("alpha", 42)
    proxy.store("beta", ["x", "y"])
    assert run_until(runtime, lambda: storage.share["item_count"] == 2,
                     timeout=5.0)
    # The S-expression wire is stringly typed (reference semantics):
    # atoms round-trip as text, structure is preserved.
    assert storage.get_local("alpha") == "42"
    assert storage.get_local("beta") == ["x", "y"]

    # fetch over the wire
    responses = []
    response_topic = f"{runtime.topic_path_process}/test_fetch"
    runtime.add_message_handler(
        lambda t, p: responses.append(p), response_topic)
    proxy.fetch(response_topic, "alpha")
    assert run_until(runtime,
                     lambda: any("item" in r and "42" in r
                                 for r in responses),
                     timeout=5.0)

    proxy.erase("alpha")
    assert run_until(runtime, lambda: storage.share["item_count"] == 1,
                     timeout=5.0)
    assert storage.get_local("alpha") is None

    # persistence across instances
    storage.stop()
    reopened = Storage(name="storage2",
                       database_path=str(tmp_path / "kv.db"),
                       runtime=runtime)
    assert reopened.get_local("beta") == ["x", "y"]
    reopened.stop()
