"""Control-plane integration tests over the in-memory loopback broker:
registrar election, service registration, LWT reaping, EC share
replication, remote proxies, discovery.  This is the offline multi-service
harness the reference cannot provide (its null transport delivers nothing;
reference tests skip registrar/share entirely -- SURVEY.md section 4)."""

from conftest import run_until

from aiko_services_tpu.runtime import ConnectionState
from aiko_services_tpu.services import (
    Actor, Registrar, ServiceFilter, ECConsumer, get_service_proxy,
    do_command, do_request)
from aiko_services_tpu.transport import get_broker


class EchoActor(Actor):
    PROTOCOL = "test/echo:0"

    def __init__(self, name, runtime=None):
        super().__init__(name, self.PROTOCOL, tags=["role=echo"],
                         runtime=runtime)
        self.calls = []

    def hello(self, name):
        self.calls.append(name)

    def ask(self, response_topic, question):
        self.runtime.message.publish(response_topic, "(item_count 1)")
        self.runtime.message.publish(response_topic,
                                     f"(response {question}!)")


def test_registrar_election_and_registration(runtime):
    registrar = Registrar(runtime=runtime, primary_search_timeout=0.05)
    actor = EchoActor("echo_1", runtime=runtime)

    assert run_until(
        runtime,
        lambda: (registrar.state == "primary"
                 and runtime.connection.state == ConnectionState.REGISTRAR
                 and registrar.registry.get(actor.topic_path) is not None),
        timeout=5.0)
    record = registrar.registry.get(actor.topic_path)
    assert record.name == "echo_1"
    assert record.protocol == EchoActor.PROTOCOL
    assert "role=echo" in record.tags


def test_second_registrar_becomes_secondary(runtime):
    primary = Registrar("registrar_a", runtime=runtime,
                        primary_search_timeout=0.05)
    run_until(runtime, lambda: primary.state == "primary")
    secondary = Registrar("registrar_b", runtime=runtime,
                          primary_search_timeout=0.05)
    assert run_until(runtime, lambda: secondary.state == "secondary")
    assert primary.state == "primary"


def test_lwt_reaps_dead_process_services(runtime):
    registrar = Registrar(runtime=runtime, primary_search_timeout=0.05)
    actor = EchoActor("echo_dead", runtime=runtime)
    run_until(runtime,
              lambda: registrar.registry.get(actor.topic_path) is not None)

    # Simulate another process dying: its LWT "(absent)" fires on its
    # process state topic.  Use a fake foreign process topic.
    foreign = f"{runtime.namespace}/otherhost/999/1"
    runtime.message.publish(
        f"{registrar.topic_path}/in",
        f"(add {foreign} ghost test/ghost:0 loopback nobody ())")
    run_until(runtime,
              lambda: registrar.registry.get(foreign) is not None)
    get_broker().publish(f"{runtime.namespace}/otherhost/999/0/state",
                         "(absent)")
    assert run_until(runtime,
                     lambda: registrar.registry.get(foreign) is None)
    # Local process services survive.
    assert registrar.registry.get(actor.topic_path) is not None


def test_remote_proxy_invocation(runtime):
    actor = EchoActor("echo_proxy", runtime=runtime)
    proxy = get_service_proxy(runtime, actor.topic_path)
    proxy.hello("world")
    assert run_until(runtime, lambda: actor.calls == ["world"])


def test_ec_share_replication(runtime):
    producer_actor = EchoActor("echo_share", runtime=runtime)
    cache = {}
    consumer = ECConsumer(runtime, producer_actor.topic_path, cache,
                          lease_time=60.0)
    assert run_until(runtime, lambda: consumer.synced)
    assert cache["name"] == "echo_share"
    assert cache["lifecycle"] == "ready"

    producer_actor.ec_producer.update("custom", "42")
    assert run_until(runtime, lambda: cache.get("custom") == "42")

    producer_actor.ec_producer.remove("custom")
    assert run_until(runtime, lambda: "custom" not in cache)


def test_ec_remote_update_changes_log_level(runtime):
    actor = EchoActor("echo_loglevel", runtime=runtime)
    runtime.message.publish(f"{actor.topic_path}/control",
                            "(update log_level DEBUG)")
    assert run_until(runtime,
                     lambda: actor.share.get("log_level") == "DEBUG")


def test_do_command_via_discovery(runtime):
    registrar = Registrar(runtime=runtime, primary_search_timeout=0.05)
    actor = EchoActor("echo_cmd", runtime=runtime)
    do_command(runtime, EchoActor,
               ServiceFilter(name="echo_cmd"),
               lambda proxy: proxy.hello("discovered"))
    assert run_until(runtime, lambda: actor.calls == ["discovered"])


def test_do_request_response(runtime):
    registrar = Registrar(runtime=runtime, primary_search_timeout=0.05)
    actor = EchoActor("echo_req", runtime=runtime)
    responses = []
    do_request(runtime, EchoActor, ServiceFilter(name="echo_req"),
               lambda proxy, response_topic: proxy.ask(response_topic,
                                                       "ping"),
               responses.append)
    assert run_until(runtime, lambda: bool(responses))
    assert responses[0] == [("response", ["ping!"])]


def test_share_query_to_registrar(runtime):
    """ServicesCache-level query: ask the registrar directory directly."""
    registrar = Registrar(runtime=runtime, primary_search_timeout=0.05)
    actor_a = EchoActor("query_a", runtime=runtime)
    actor_b = EchoActor("query_b", runtime=runtime)
    run_until(runtime,
              lambda: registrar.registry.get(actor_b.topic_path) is not None)

    got = []
    response_topic = f"{runtime.topic_path_process}/testq"
    runtime.add_message_handler(lambda t, p: got.append(p), response_topic)
    runtime.message.publish(
        f"{registrar.topic_path}/in",
        f"(share {response_topic} * query_a * * * *)")
    assert run_until(runtime,
                     lambda: any("sync" in p for p in got))
    adds = [p for p in got if p.startswith("(add")]
    assert len(adds) == 1 and "query_a" in adds[0]


def test_stale_primary_record_is_cleared_and_superseded(runtime):
    """A retained (primary found) left by a registrar that died without
    its will firing must not pin later registrars in secondary: the
    probe detects the dead primary, clears the stale record, and the
    live registrar promotes itself (the condition the reference clears
    manually via system_reset.sh)."""
    from aiko_services_tpu.services import Registrar
    from aiko_services_tpu.utils import generate

    # Fabricate the stale record: a plausible but dead topic path.
    runtime.message.publish(
        runtime.topic_registrar_boot,
        generate("primary",
                 ["found", f"{runtime.namespace}/deadhost/1/0", "v0",
                  1.0]),
        retain=True)

    registrar = Registrar(runtime=runtime, primary_search_timeout=0.05)
    registrar._probe_interval = 0.1          # fast probe for the test
    assert run_until(runtime, lambda: registrar.state == "secondary",
                     timeout=5.0)
    # Probe goes unanswered twice -> stale record cleared -> promotion.
    assert run_until(runtime, lambda: registrar.state == "primary",
                     timeout=10.0), "stale primary never superseded"
    assert registrar._probe_timer is None
