"""Weight-only int8 quantization (models/quant.py): exactness on
grid-aligned weights, bounded error on arbitrary ones, and the serving
paths running unchanged on a quantized tree."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from aiko_services_tpu.models import llama
from aiko_services_tpu.models.quant import (QUANTIZED_LAYER_KEYS,
                                            is_quantized, quantize_params,
                                            quantize_weight)


def grid_aligned_params(config):
    """Params whose matmul weights sit exactly on an int8 grid, so
    quantization is lossless and quant-vs-raw forward must agree to
    float rounding only."""
    params = llama.init_params(jax.random.PRNGKey(0), config)
    key = jax.random.PRNGKey(42)

    def align(weight):
        nonlocal key
        key, sub1, sub2 = jax.random.split(key, 3)
        levels = jax.random.randint(sub1, weight.shape, -127, 128)
        # Pin level 127 in every output channel so quantization recovers
        # exactly this scale (scale = channel max / 127).
        levels = levels.at[..., 0, :].set(127)
        scale = jax.random.uniform(sub2, weight.shape[-1:],
                                   minval=0.5, maxval=2.0) / 127.0
        return (levels * scale).astype(weight.dtype) * 0.05

    layers = dict(params["layers"])
    for name in QUANTIZED_LAYER_KEYS:
        layers[name] = align(layers[name])
    params["layers"] = layers
    params["unembed"] = align(params["unembed"])
    return params


def test_quantize_tree_structure():
    config = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), config)
    quantized = quantize_params(params)
    for name in QUANTIZED_LAYER_KEYS:
        leaf = quantized["layers"][name]
        assert is_quantized(leaf)
        assert leaf["int8"].dtype == jnp.int8
        assert leaf["int8"].shape == params["layers"][name].shape
        assert leaf["scale"].shape[-1] == leaf["int8"].shape[-1]
    assert is_quantized(quantized["unembed"])
    assert not is_quantized(quantized["embed"])
    # ~2x smaller where it counts.
    raw = params["layers"]["w_gate"].nbytes
    packed = quantized["layers"]["w_gate"]["int8"].nbytes \
        + quantized["layers"]["w_gate"]["scale"].nbytes
    assert packed < raw * 0.55


def test_quantize_roundtrip_error_bounded():
    weight = jax.random.normal(jax.random.PRNGKey(1), (64, 128),
                               jnp.float32)
    q = quantize_weight(weight)
    rebuilt = q["int8"].astype(jnp.float32) * q["scale"].astype(
        jnp.float32)
    per_channel_max = jnp.abs(weight).max(axis=0)
    error = jnp.abs(rebuilt - weight).max(axis=0)
    # Symmetric int8: error <= half a step = max/254 per channel.
    assert bool((error <= per_channel_max / 254 + 1e-7).all())


def test_quantized_forward_matches_on_grid_weights():
    """Grid-aligned weights quantize losslessly: prefill + decode on the
    quantized tree match the raw tree to float tolerance."""
    config = dataclasses.replace(
        llama.LlamaConfig.tiny(vocab_size=256, max_seq=32),
        dtype="float32")
    params = grid_aligned_params(config)
    quantized = quantize_params(params)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 9), 0, 256)

    raw_logits, raw_cache = llama.prefill(
        params, config, tokens[:, :8], llama.init_cache(config, 2, 32),
        jnp.zeros(2, dtype=jnp.int32))
    q_logits, q_cache = llama.prefill(
        quantized, config, tokens[:, :8],
        llama.init_cache(config, 2, 32), jnp.zeros(2, dtype=jnp.int32))
    np.testing.assert_allclose(np.asarray(raw_logits),
                               np.asarray(q_logits), atol=2e-3)

    raw_step, _ = llama.decode_step(params, config, tokens[:, 8],
                                    raw_cache,
                                    jnp.full((2,), 8, jnp.int32))
    q_step, _ = llama.decode_step(quantized, config, tokens[:, 8],
                                  q_cache, jnp.full((2,), 8, jnp.int32))
    np.testing.assert_allclose(np.asarray(raw_step),
                               np.asarray(q_step), atol=2e-3)


def test_batcher_serves_quantized_params():
    """The continuous batcher runs unchanged on a quantized tree (jit
    treats the {"int8","scale"} dicts as ordinary pytree leaves)."""
    from aiko_services_tpu.models import ContinuousBatcher, Request
    from aiko_services_tpu.models.tokenizer import ByteTokenizer

    config = llama.LlamaConfig.tiny()
    params = quantize_params(
        llama.init_params(jax.random.PRNGKey(0), config))
    tok = ByteTokenizer()
    out = []
    batcher = ContinuousBatcher(params, config, max_slots=2, max_seq=64,
                                prefill_chunk=16)
    batcher.submit(Request("r1", tok.encode("aloha"), max_new_tokens=5,
                           emit=lambda r, t, f: out.append(t)))
    steps = batcher.run_until_drained(max_steps=200)
    assert steps < 200
    assert len(out) == 5
