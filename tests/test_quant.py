"""int8 quantization (models/quant.py): weight-only exactness on
grid-aligned weights, bounded error on arbitrary ones, the serving
paths running unchanged on a quantized tree, TP/fsdp sharding of the
quantized tree (quantize_specs), and the int8 KV cache."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from aiko_services_tpu.models import llama
from aiko_services_tpu.models.quant import (QUANTIZED_LAYER_KEYS,
                                            dequantize_kv, is_quantized,
                                            quantize_kv, quantize_params,
                                            quantize_specs,
                                            quantize_weight)
from aiko_services_tpu.parallel import MeshPlan, P


def grid_aligned_params(config):
    """Params whose matmul weights sit exactly on an int8 grid, so
    quantization is lossless and quant-vs-raw forward must agree to
    float rounding only."""
    params = llama.init_params(jax.random.PRNGKey(0), config)
    key = jax.random.PRNGKey(42)

    def align(weight):
        nonlocal key
        key, sub1, sub2 = jax.random.split(key, 3)
        levels = jax.random.randint(sub1, weight.shape, -127, 128)
        # Pin level 127 in every output channel so quantization recovers
        # exactly this scale (scale = channel max / 127).
        levels = levels.at[..., 0, :].set(127)
        scale = jax.random.uniform(sub2, weight.shape[-1:],
                                   minval=0.5, maxval=2.0) / 127.0
        return (levels * scale).astype(weight.dtype) * 0.05

    layers = dict(params["layers"])
    for name in QUANTIZED_LAYER_KEYS:
        layers[name] = align(layers[name])
    params["layers"] = layers
    params["unembed"] = align(params["unembed"])
    return params


def test_quantize_tree_structure():
    config = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), config)
    quantized = quantize_params(params)
    for name in QUANTIZED_LAYER_KEYS:
        leaf = quantized["layers"][name]
        assert is_quantized(leaf)
        assert leaf["int8"].dtype == jnp.int8
        assert leaf["int8"].shape == params["layers"][name].shape
        assert leaf["scale"].shape[-1] == leaf["int8"].shape[-1]
    assert is_quantized(quantized["unembed"])
    assert not is_quantized(quantized["embed"])
    # ~2x smaller where it counts.
    raw = params["layers"]["w_gate"].nbytes
    packed = quantized["layers"]["w_gate"]["int8"].nbytes \
        + quantized["layers"]["w_gate"]["scale"].nbytes
    assert packed < raw * 0.55


def test_quantize_roundtrip_error_bounded():
    weight = jax.random.normal(jax.random.PRNGKey(1), (64, 128),
                               jnp.float32)
    q = quantize_weight(weight)
    rebuilt = q["int8"].astype(jnp.float32) * q["scale"].astype(
        jnp.float32)
    per_channel_max = jnp.abs(weight).max(axis=0)
    error = jnp.abs(rebuilt - weight).max(axis=0)
    # Symmetric int8: error <= half a step = max/254 per channel.
    assert bool((error <= per_channel_max / 254 + 1e-7).all())


def test_quantized_forward_matches_on_grid_weights():
    """Grid-aligned weights quantize losslessly: prefill + decode on the
    quantized tree match the raw tree to float tolerance."""
    config = dataclasses.replace(
        llama.LlamaConfig.tiny(vocab_size=256, max_seq=32),
        dtype="float32")
    params = grid_aligned_params(config)
    quantized = quantize_params(params)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 9), 0, 256)

    raw_logits, raw_cache = llama.prefill(
        params, config, tokens[:, :8], llama.init_cache(config, 2, 32),
        jnp.zeros(2, dtype=jnp.int32))
    q_logits, q_cache = llama.prefill(
        quantized, config, tokens[:, :8],
        llama.init_cache(config, 2, 32), jnp.zeros(2, dtype=jnp.int32))
    np.testing.assert_allclose(np.asarray(raw_logits),
                               np.asarray(q_logits), atol=2e-3)

    raw_step, _ = llama.decode_step(params, config, tokens[:, 8],
                                    raw_cache,
                                    jnp.full((2,), 8, jnp.int32))
    q_step, _ = llama.decode_step(quantized, config, tokens[:, 8],
                                  q_cache, jnp.full((2,), 8, jnp.int32))
    np.testing.assert_allclose(np.asarray(raw_step),
                               np.asarray(q_step), atol=2e-3)


def test_batcher_serves_quantized_params():
    """The continuous batcher runs unchanged on a quantized tree (jit
    treats the {"int8","scale"} dicts as ordinary pytree leaves)."""
    from aiko_services_tpu.models import ContinuousBatcher, Request
    from aiko_services_tpu.models.tokenizer import ByteTokenizer

    config = llama.LlamaConfig.tiny()
    params = quantize_params(
        llama.init_params(jax.random.PRNGKey(0), config))
    tok = ByteTokenizer()
    out = []
    batcher = ContinuousBatcher(params, config, max_slots=2, max_seq=64,
                                prefill_chunk=16)
    batcher.submit(Request("r1", tok.encode("aloha"), max_new_tokens=5,
                           emit=lambda r, t, f: out.append(t)))
    steps = batcher.run_until_drained(max_steps=200)
    assert steps < 200
    assert len(out) == 5


# -- TP / fsdp composition (VERDICT r2 item 4) ---------------------------


def test_quantize_specs_mirror_quantized_tree():
    """quantize_specs produces a spec tree with the quantized params'
    exact structure: tree_map over (params, specs) must not raise."""
    config = llama.LlamaConfig.tiny()
    params = quantize_params(
        llama.init_params(jax.random.PRNGKey(0), config))
    specs = quantize_specs(llama.partition_specs(config))
    paired = jax.tree_util.tree_map(lambda leaf, s: (leaf.shape, s),
                                    params, specs)
    wq = paired["layers"]["wq"]
    assert wq["int8"][1] == P(None, "fsdp", "tp")
    # Scale cannot shard its size-1 contraction axis.
    assert wq["scale"][1] == P(None, None, "tp")
    assert paired["unembed"]["scale"][1] == P(None, "tp")


def test_tp_decode_with_quantized_tree():
    """TP/fsdp-sharded quantized tree decodes on the 8-device mesh and
    matches the unsharded quantized decode."""
    config = dataclasses.replace(
        llama.LlamaConfig.tiny(vocab_size=256, max_seq=32),
        dtype="float32")
    params = quantize_params(grid_aligned_params(config))
    plan = MeshPlan.build({"dp": 2, "fsdp": 2, "tp": 2})
    sharded = plan.put(params, quantize_specs(
        llama.partition_specs(config)))
    cache_sharding = jax.tree_util.tree_map(
        plan.shard, llama.cache_specs(config))
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 9), 0, 256)

    _, ref_cache = llama.prefill(params, config, tokens[:, :8],
                                 llama.init_cache(config, 2, 32),
                                 jnp.zeros(2, dtype=jnp.int32))
    ref_step, _ = llama.decode_step(params, config, tokens[:, 8],
                                    ref_cache,
                                    jnp.full((2,), 8, jnp.int32))

    cache = jax.device_put(llama.init_cache(config, 2, 32),
                           cache_sharding)
    _, cache = llama.prefill(sharded, config,
                             jax.device_put(tokens[:, :8],
                                            plan.shard(P("dp", None))),
                             cache, jnp.zeros(2, dtype=jnp.int32))
    tp_step, _ = llama.decode_step(sharded, config, tokens[:, 8], cache,
                                   jnp.full((2,), 8, jnp.int32))
    np.testing.assert_allclose(np.asarray(tp_step, dtype=np.float32),
                               np.asarray(ref_step, dtype=np.float32),
                               atol=2e-3)


# -- int8 KV cache (VERDICT r2 item 4) -----------------------------------


def test_kv_quantized_attention_matches_dequantized():
    """Prefill over a quantized cache equals attention over the
    explicitly dequantized cache to float rounding (the scale folding
    is exact math).  The decode-append path ADDITIONALLY quantizes the
    query and the softmax weights so both cache matmuls run as native
    int8 MXU dots (ops/layers.py) -- bounded-approximate there, with
    error at the int8 step size, not float rounding."""
    from aiko_services_tpu.ops.layers import (attention_decode_append,
                                              attention_prefill)
    key = jax.random.PRNGKey(0)
    b, s, t, h, kv, hd = 2, 4, 16, 4, 2, 8
    q = jax.random.normal(key, (b, s, h, hd), dtype=jnp.float32)
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, t, kv, hd),
                          dtype=jnp.float32)
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, t, kv, hd),
                          dtype=jnp.float32)
    kq, vq = quantize_kv(k), quantize_kv(v)
    kd = dequantize_kv(kq, jnp.float32)
    vd = dequantize_kv(vq, jnp.float32)
    positions = jnp.tile(jnp.arange(4, 4 + s)[None, :], (b, 1))
    with jax.default_matmul_precision("highest"):
        np.testing.assert_allclose(
            np.asarray(attention_prefill(q, kq, vq, positions)),
            np.asarray(attention_prefill(q, kd, vd, positions)),
            atol=1e-5)
        k_new = jax.random.normal(jax.random.fold_in(key, 3),
                                  (b, 1, kv, hd), dtype=jnp.float32)
        v_new = jax.random.normal(jax.random.fold_in(key, 4),
                                  (b, 1, kv, hd), dtype=jnp.float32)
        lengths = jnp.array([5, 9])
        np.testing.assert_allclose(
            np.asarray(attention_decode_append(q[:, :1], kq, vq, k_new,
                                               v_new, lengths)),
            np.asarray(attention_decode_append(q[:, :1], kd, vd, k_new,
                                               v_new, lengths)),
            atol=3e-2)


def test_kv_cache_int8_serving_paths():
    """kv_dtype="int8": prefill/prefill_into_slot/decode_step run on the
    quantized cache and track the bf16-cache logits closely (per-token
    scales bound the cache error at ~0.4%)."""
    base = dataclasses.replace(
        llama.LlamaConfig.tiny(vocab_size=256, max_seq=32),
        dtype="float32")
    int8 = dataclasses.replace(base, kv_dtype="int8")
    params = llama.init_params(jax.random.PRNGKey(0), base)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 9), 0, 256)

    logits_a, cache_a = llama.prefill(
        params, base, tokens[:, :8], llama.init_cache(base, 2, 32),
        jnp.zeros(2, dtype=jnp.int32))
    logits_b, cache_b = llama.prefill(
        params, int8, tokens[:, :8], llama.init_cache(int8, 2, 32),
        jnp.zeros(2, dtype=jnp.int32))
    assert cache_b["k"]["int8"].dtype == jnp.int8
    assert cache_b["k"]["scale"].shape == (base.n_layers, 2, 32,
                                           base.n_kv_heads, 1)
    np.testing.assert_allclose(np.asarray(logits_a),
                               np.asarray(logits_b), atol=5e-2)

    step_a, _ = llama.decode_step(params, base, tokens[:, 8], cache_a,
                                  jnp.full((2,), 8, jnp.int32))
    step_b, _ = llama.decode_step(params, int8, tokens[:, 8], cache_b,
                                  jnp.full((2,), 8, jnp.int32))
    np.testing.assert_allclose(np.asarray(step_a), np.asarray(step_b),
                               atol=5e-2)

    # Slot admission writes the quantized cache in place.
    cache = llama.init_cache(int8, 2, 32)
    logits, cache = llama.prefill_into_slot(
        params, int8, tokens[:1, :8], cache, jnp.int32(1), jnp.int32(0))
    np.testing.assert_allclose(np.asarray(logits[0]),
                               np.asarray(logits_b[0]), atol=5e-2)
    assert int(np.abs(np.asarray(cache["k"]["int8"][:, 0])).max()) == 0


def test_kv_cache_int8_halves_cache_bytes():
    int8 = dataclasses.replace(llama.LlamaConfig.tiny(),
                               kv_dtype="int8")
    cache = llama.init_cache(int8, 2, 32)
    bf16 = llama.init_cache(llama.LlamaConfig.tiny(), 2, 32)
    quantized_bytes = cache["k"]["int8"].nbytes \
        + cache["k"]["scale"].nbytes
    # Ratio = (hd + 4) / (2*hd): 0.625 at the tiny config's hd=16,
    # 0.53 at a real model's hd=64.
    hd = int8.head_dim
    assert quantized_bytes == bf16["k"].nbytes * (hd + 4) / (2 * hd)


def test_batcher_serves_int8_kv_cache():
    """End-to-end serving on int8 weights AND int8 KV cache, pipelined
    fused-block path included; token streams keep their budget/EOS
    semantics."""
    from aiko_services_tpu.models import ContinuousBatcher, Request
    from aiko_services_tpu.models.tokenizer import ByteTokenizer

    config = dataclasses.replace(llama.LlamaConfig.tiny(),
                                 kv_dtype="int8")
    params = quantize_params(
        llama.init_params(jax.random.PRNGKey(0), config))
    tok = ByteTokenizer()
    emitted = {}

    def emit(request_id, token, finished):
        emitted.setdefault(request_id, []).append(token)

    batcher = ContinuousBatcher(params, config, max_slots=2, max_seq=64,
                                prefill_chunk=16, decode_block=4,
                                inflight=2)
    for i in range(3):
        batcher.submit(Request(f"r{i}", tok.encode(f"aloha {i}"),
                               max_new_tokens=6, emit=emit))
    steps = batcher.run_until_drained(max_steps=300)
    assert steps < 300
    assert sorted(emitted) == ["r0", "r1", "r2"]
    assert all(len(tokens) == 6 for tokens in emitted.values())


def test_batcher_tp_sharded_quantized_serving():
    """The flagship multichip serving config: TP-sharded quantized tree
    + TP-sharded cache through a real batcher drain on the 8-device
    mesh."""
    from aiko_services_tpu.models import ContinuousBatcher, Request

    config = llama.LlamaConfig.tiny()
    params = quantize_params(
        llama.init_params(jax.random.PRNGKey(0), config))
    plan = MeshPlan.build({"dp": 2, "fsdp": 2, "tp": 2})
    sharded = plan.put(params, quantize_specs(
        llama.partition_specs(config)))
    cache_sharding = jax.tree_util.tree_map(
        plan.shard, llama.cache_specs(config))
    out = []
    batcher = ContinuousBatcher(
        sharded, config, max_slots=2, max_seq=64, prefill_chunk=16,
        decode_block=4, inflight=2,
        cache_put=lambda c: jax.device_put(c, cache_sharding))
    batcher.submit(Request("r", [1, 2, 3], max_new_tokens=6,
                           emit=lambda r, t, f: out.append(t)))
    steps = batcher.run_until_drained(max_steps=200)
    assert steps < 200
    assert len(out) == 6
