// tensor_pipe: length-prefixed TCP tensor transport (C ABI, used via
// ctypes from aiko_services_tpu/transport/tensor_pipe.py).
//
// The framework's native bulk data plane for host<->host hops with no
// ICI path (SURVEY.md section 5.8): the reference delegates this role
// to libzmq (an external C++ dependency, reference
// elements/media/scheme_zmq.py:12); here it is part of the framework,
// a single-file library beside the native MQTT broker.
//
// Frame wire format (little-endian):
//   u32 magic 'TPIP' | u32 header_len | u64 payload_len
//   header bytes (JSON: dtype/shape/name) | payload bytes
//
// Design: blocking socket calls bounded by poll() timeouts; one OS fd
// per handle, no internal threads or buffers -- concurrency and
// framing policy live in Python, where the event model already is.
// Handles are plain fds, so the library is state-free and fork-safe.
//
// Build: g++ -O2 -std=c++17 -shared -fPIC -o libtensor_pipe.so
//        tensor_pipe.cpp

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x54504950;  // "TPIP"

int wait_readable(int fd, int timeout_ms) {
    pollfd p{fd, POLLIN, 0};
    int rc = ::poll(&p, 1, timeout_ms);
    if (rc <= 0) return -1;                       // timeout or error
    return 0;
}

// Read exactly n bytes.  Returns 0 on success, -1 on a CLEAN timeout
// (no byte consumed -- safe to retry later), -2 on close/error or a
// mid-read timeout (bytes already consumed: the stream is torn and
// the caller must drop the connection, retrying would desync).
int read_exact(int fd, void* buffer, uint64_t n, int timeout_ms) {
    auto* out = static_cast<uint8_t*>(buffer);
    uint64_t done = 0;
    while (done < n) {
        if (wait_readable(fd, timeout_ms) != 0)
            return done == 0 ? -1 : -2;
        ssize_t got = ::recv(fd, out + done, n - done, 0);
        if (got == 0) return -2;                  // peer closed (EOF)
        if (got < 0) {
            if (errno == EINTR) continue;
            return -2;
        }
        done += static_cast<uint64_t>(got);
    }
    return 0;
}

// A send that makes NO progress for this long means the peer is
// wedged (window full, reader dead), not merely slow: fail the send
// so the caller's fallback/breaker machinery can run.  Unbounded
// blocking here would freeze the sending event loop forever.
constexpr int kSendStallMs = 10000;

int write_exact(int fd, const void* buffer, uint64_t n) {
    auto* in = static_cast<const uint8_t*>(buffer);
    uint64_t done = 0;
    while (done < n) {
        ssize_t put = ::send(fd, in + done, n - done, MSG_NOSIGNAL);
        if (put < 0) {
            if (errno == EINTR) continue;
            if (errno == EAGAIN || errno == EWOULDBLOCK) {
                // Kernel buffer full (slow receiver): wait for space
                // rather than tearing the stream mid-frame -- but only
                // bounded; zero progress past the stall cap is a dead
                // peer.
                pollfd p{fd, POLLOUT, 0};
                if (::poll(&p, 1, kSendStallMs) <= 0) return -1;
                continue;
            }
            return -1;
        }
        done += static_cast<uint64_t>(put);
    }
    return 0;
}

void tune(int fd) {
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

}  // namespace

extern "C" {

// Listening socket on host:port (port 0 = kernel-assigned); returns fd
// or -1.
int tp_listen(const char* host, int port) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    int one = 1;
    ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in address{};
    address.sin_family = AF_INET;
    address.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host, &address.sin_addr) != 1) {
        ::close(fd);
        return -1;
    }
    if (::bind(fd, reinterpret_cast<sockaddr*>(&address),
               sizeof(address)) != 0
        || ::listen(fd, 16) != 0) {
        ::close(fd);
        return -1;
    }
    return fd;
}

// The actual bound port of a listening fd (for port 0 requests).
int tp_port(int fd) {
    sockaddr_in address{};
    socklen_t len = sizeof(address);
    if (::getsockname(fd, reinterpret_cast<sockaddr*>(&address),
                      &len) != 0)
        return -1;
    return ntohs(address.sin_port);
}

// Accept one connection (-1 on timeout/error).
int tp_accept(int server_fd, int timeout_ms) {
    if (wait_readable(server_fd, timeout_ms) != 0) return -1;
    int fd = ::accept(server_fd, nullptr, nullptr);
    if (fd >= 0) tune(fd);
    return fd;
}

int tp_connect(const char* host, int port, int timeout_ms) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_in address{};
    address.sin_family = AF_INET;
    address.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host, &address.sin_addr) != 1) {
        ::close(fd);
        return -1;
    }
    // Bounded connect via a temporary send timeout -- CLEARED after
    // the handshake, or a later large send stalling past it would
    // spuriously fail (EAGAIN) and tear a healthy connection.
    timeval tv{timeout_ms / 1000, (timeout_ms % 1000) * 1000};
    ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    if (::connect(fd, reinterpret_cast<sockaddr*>(&address),
                  sizeof(address)) != 0) {
        ::close(fd);
        return -1;
    }
    timeval forever{0, 0};
    ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &forever,
                 sizeof(forever));
    tune(fd);
    return fd;
}

// One framed message: header + payload in a single call.
int tp_send(int fd, const void* header, uint32_t header_len,
            const void* payload, uint64_t payload_len) {
    uint8_t prefix[16];
    uint32_t magic = kMagic;
    std::memcpy(prefix, &magic, 4);
    std::memcpy(prefix + 4, &header_len, 4);
    std::memcpy(prefix + 8, &payload_len, 8);
    if (write_exact(fd, prefix, sizeof(prefix)) != 0) return -1;
    if (header_len && write_exact(fd, header, header_len) != 0)
        return -1;
    if (payload_len && write_exact(fd, payload, payload_len) != 0)
        return -1;
    return 0;
}

// Frame sanity caps: a desynced or hostile peer must not drive
// allocations from 8 arbitrary wire bytes.
constexpr uint32_t kMaxHeader = 1u << 20;         // 1 MiB of JSON
constexpr uint64_t kMaxPayload = 1ull << 32;      // 4 GiB per tensor

// Phase 1: read the frame prefix -> header/payload lengths (so the
// caller can allocate).  Returns 0 ok, -1 clean timeout (retry),
// -2 closed/torn (drop the connection), -3 corrupt (bad magic or an
// absurd length -- drop the connection).
int tp_recv_begin(int fd, int timeout_ms, uint32_t* header_len,
                  uint64_t* payload_len) {
    uint8_t prefix[16];
    int rc = read_exact(fd, prefix, sizeof(prefix), timeout_ms);
    if (rc != 0) return rc;
    uint32_t magic;
    std::memcpy(&magic, prefix, 4);
    if (magic != kMagic) return -3;               // stream corrupt
    std::memcpy(header_len, prefix + 4, 4);
    std::memcpy(payload_len, prefix + 8, 8);
    if (*header_len > kMaxHeader || *payload_len > kMaxPayload)
        return -3;
    return 0;
}

// Phase 2: read the announced bytes into caller buffers.  Any failure
// here means a torn frame: returns -2 (drop the connection).
int tp_recv_body(int fd, void* header, uint32_t header_len,
                 void* payload, uint64_t payload_len, int timeout_ms) {
    if (header_len
        && read_exact(fd, header, header_len, timeout_ms) != 0)
        return -2;
    if (payload_len
        && read_exact(fd, payload, payload_len, timeout_ms) != 0)
        return -2;
    return 0;
}

void tp_close(int fd) {
    if (fd >= 0) ::close(fd);
}

}  // extern "C"
