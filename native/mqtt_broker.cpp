// mqtt_broker: a single-file MQTT 3.1.1 broker for the aiko control
// plane (the native-fabric role mosquitto plays for the reference --
// reference scripts/system_start.sh:28-56 launches mosquitto; this
// broker is in-tree so single-host deployments and integration tests
// need no external daemon).
//
// Scope (exactly what the framework's control plane uses):
//   - CONNECT/CONNACK (client id, clean session, keepalive, will
//     topic/message/retain; username/password accepted and ignored)
//   - PUBLISH QoS 0 and QoS 1 (PUBACK to the publisher; delivery to
//     subscribers is downgraded to QoS 0 -- at-most-once fan-out)
//   - retained messages (empty retained payload clears, MQTT-3.3.1-10)
//   - SUBSCRIBE/SUBACK with '+' and trailing '#' wildcards, retained
//     delivery on subscribe; UNSUBSCRIBE/UNSUBACK
//   - PINGREQ/PINGRESP; DISCONNECT clears the will (MQTT-3.14.4-3)
//   - last-will published on any abnormal disconnect -- the liveness
//     signal the Registrar's failure detection rides on
//
// Single thread, poll(2) loop, no dependencies.  Not implemented (not
// needed by the framework): QoS 2, session persistence, TLS (front
// with stunnel/nginx if required), MQTT 5.
//
// Build:  g++ -O2 -std=c++17 -o mqtt_broker mqtt_broker.cpp
// Run:    ./mqtt_broker [port]        (0 = kernel-assigned; the chosen
//                                      port is printed as "LISTENING <port>")

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <map>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <set>
#include <signal.h>
#include <string>
#include <sys/socket.h>
#include <unistd.h>
#include <vector>

namespace {

constexpr size_t kMaxPacket = 4 * 1024 * 1024;   // headroom over the
// control plane's largest payloads (share snapshots, base64 frames).

struct Client {
    int fd = -1;
    std::string inbuf;
    std::string outbuf;
    std::string client_id;
    std::set<std::string> filters;
    bool connected = false;       // CONNECT processed
    bool has_will = false;
    std::string will_topic, will_payload;
    bool will_retain = false;
    uint16_t keepalive = 0;       // seconds; 0 = no timeout
    time_t last_activity = 0;
};

std::map<int, Client> clients;                     // fd -> client
std::map<std::string, std::string> retained;       // topic -> payload

// -- topic matching ---------------------------------------------------------

std::vector<std::string> split_levels(const std::string& path) {
    std::vector<std::string> levels;
    size_t start = 0;
    for (;;) {
        size_t slash = path.find('/', start);
        if (slash == std::string::npos) {
            levels.push_back(path.substr(start));
            return levels;
        }
        levels.push_back(path.substr(start, slash - start));
        start = slash + 1;
    }
}

bool topic_matches(const std::string& filter, const std::string& topic) {
    std::vector<std::string> flevels = split_levels(filter);
    std::vector<std::string> tlevels = split_levels(topic);
    for (size_t i = 0; i < flevels.size(); ++i) {
        if (flevels[i] == "#") return true;        // rest of the topic
        if (i >= tlevels.size()) return false;
        if (flevels[i] != "+" && flevels[i] != tlevels[i]) return false;
    }
    return flevels.size() == tlevels.size();
}

// -- packet building --------------------------------------------------------

void put_remaining_length(std::string& out, size_t length) {
    do {
        uint8_t digit = length % 128;
        length /= 128;
        if (length > 0) digit |= 0x80;
        out.push_back(static_cast<char>(digit));
    } while (length > 0);
}

std::string make_publish(const std::string& topic,
                         const std::string& payload, bool retain) {
    std::string packet;
    packet.push_back(static_cast<char>(0x30 | (retain ? 0x01 : 0x00)));
    std::string body;
    body.push_back(static_cast<char>(topic.size() >> 8));
    body.push_back(static_cast<char>(topic.size() & 0xff));
    body += topic;
    body += payload;                               // QoS 0: no packet id
    put_remaining_length(packet, body.size());
    packet += body;
    return packet;
}

void queue_out(Client& client, const std::string& packet) {
    client.outbuf += packet;
}

// -- routing ----------------------------------------------------------------

void route_publish(const std::string& topic, const std::string& payload,
                   bool retain) {
    if (retain) {
        if (payload.empty()) retained.erase(topic);
        else retained[topic] = payload;
    }
    // Deliver with the retain flag CLEAR (it is a live message,
    // MQTT-3.3.1-9).
    std::string packet = make_publish(topic, payload, false);
    for (auto& [fd, client] : clients) {
        if (!client.connected) continue;
        for (const auto& filter : client.filters) {
            if (topic_matches(filter, topic)) {
                queue_out(client, packet);
                break;
            }
        }
    }
}

void publish_will(Client& client) {
    if (client.has_will) {
        route_publish(client.will_topic, client.will_payload,
                      client.will_retain);
        client.has_will = false;
    }
}

// -- packet parsing ---------------------------------------------------------

uint16_t read_u16(const std::string& data, size_t offset) {
    return (static_cast<uint8_t>(data[offset]) << 8)
         | static_cast<uint8_t>(data[offset + 1]);
}

// Returns false when the client must be dropped (protocol error).
bool handle_packet(Client& client, uint8_t header,
                   const std::string& body) {
    uint8_t type = header >> 4;
    switch (type) {
    case 1: {                                      // CONNECT
        // variable header: proto name (len-prefixed), level, flags,
        // keepalive -- then payload: client id [, will topic, will msg]
        // [, username] [, password].
        if (body.size() < 10) return false;
        size_t name_length = read_u16(body, 0);
        size_t at = 2 + name_length;               // skip protocol name
        if (at + 4 > body.size()) return false;
        at += 1;                                   // protocol level
        uint8_t flags = static_cast<uint8_t>(body[at]); at += 1;
        client.keepalive = read_u16(body, at); at += 2;
        if (at + 2 > body.size()) return false;
        size_t id_length = read_u16(body, at); at += 2;
        if (at + id_length > body.size()) return false;
        client.client_id = body.substr(at, id_length); at += id_length;
        if (flags & 0x04) {                        // will flag
            if (at + 2 > body.size()) return false;
            size_t wt = read_u16(body, at); at += 2;
            if (at + wt > body.size()) return false;
            client.will_topic = body.substr(at, wt); at += wt;
            if (at + 2 > body.size()) return false;
            size_t wp = read_u16(body, at); at += 2;
            if (at + wp > body.size()) return false;
            client.will_payload = body.substr(at, wp); at += wp;
            client.will_retain = (flags & 0x20) != 0;
            client.has_will = true;
        }
        client.connected = true;
        queue_out(client, std::string("\x20\x02\x00\x00", 4)); // CONNACK
        return true;
    }
    case 3: {                                      // PUBLISH
        uint8_t qos = (header >> 1) & 0x03;
        bool retain = (header & 0x01) != 0;
        if (body.size() < 2) return false;
        size_t topic_length = read_u16(body, 0);
        size_t at = 2 + topic_length;
        if (at > body.size()) return false;
        std::string topic = body.substr(2, topic_length);
        if (qos > 0) {
            if (at + 2 > body.size()) return false;
            uint16_t packet_id = read_u16(body, at); at += 2;
            std::string puback("\x40\x02", 2);     // PUBACK
            puback.push_back(static_cast<char>(packet_id >> 8));
            puback.push_back(static_cast<char>(packet_id & 0xff));
            queue_out(client, puback);
        }
        route_publish(topic, body.substr(at), retain);
        return true;
    }
    case 8: {                                      // SUBSCRIBE
        if (body.size() < 2) return false;
        uint16_t packet_id = read_u16(body, 0);
        size_t at = 2;
        std::vector<std::string> added;
        while (at + 2 <= body.size()) {
            size_t flen = read_u16(body, at); at += 2;
            if (at + flen + 1 > body.size()) return false;
            std::string filter = body.substr(at, flen);
            at += flen + 1;                        // + requested QoS
            client.filters.insert(filter);
            added.push_back(filter);
        }
        std::string suback("\x90", 1);
        std::string sbody;
        sbody.push_back(static_cast<char>(packet_id >> 8));
        sbody.push_back(static_cast<char>(packet_id & 0xff));
        sbody.append(added.size(), '\x00');        // granted QoS 0
        put_remaining_length(suback, sbody.size());
        suback += sbody;
        queue_out(client, suback);
        for (const auto& filter : added)           // retained delivery
            for (const auto& [topic, payload] : retained)
                if (topic_matches(filter, topic))
                    queue_out(client,
                              make_publish(topic, payload, true));
        return true;
    }
    case 10: {                                     // UNSUBSCRIBE
        if (body.size() < 2) return false;
        uint16_t packet_id = read_u16(body, 0);
        size_t at = 2;
        while (at + 2 <= body.size()) {
            size_t flen = read_u16(body, at); at += 2;
            if (at + flen > body.size()) return false;
            client.filters.erase(body.substr(at, flen));
            at += flen;
        }
        std::string unsuback("\xb0\x02", 2);
        unsuback.push_back(static_cast<char>(packet_id >> 8));
        unsuback.push_back(static_cast<char>(packet_id & 0xff));
        queue_out(client, unsuback);
        return true;
    }
    case 12:                                       // PINGREQ
        queue_out(client, std::string("\xd0\x00", 2));
        return true;
    case 14:                                       // DISCONNECT
        client.has_will = false;                   // graceful: no will
        return false;                              // close connection
    default:                                       // QoS2 flow etc.
        return false;
    }
}

// Drain complete packets from a client's input buffer.
bool process_input(Client& client) {
    for (;;) {
        if (client.inbuf.size() < 2) return true;
        uint8_t header = static_cast<uint8_t>(client.inbuf[0]);
        size_t remaining = 0, multiplier = 1, at = 1;
        bool length_complete = false;
        while (at < client.inbuf.size() && at <= 4) {
            uint8_t digit = static_cast<uint8_t>(client.inbuf[at]);
            remaining += (digit & 0x7f) * multiplier;
            multiplier *= 128;
            at += 1;
            if (!(digit & 0x80)) { length_complete = true; break; }
        }
        if (!length_complete)
            return client.inbuf.size() <= 5;       // malformed if >5
        if (remaining > kMaxPacket) return false;
        if (client.inbuf.size() < at + remaining) return true;
        std::string body = client.inbuf.substr(at, remaining);
        client.inbuf.erase(0, at + remaining);
        if (!handle_packet(client, header, body)) return false;
    }
}

void drop_client(int fd, bool abnormal) {
    auto it = clients.find(fd);
    if (it == clients.end()) return;
    if (abnormal) publish_will(it->second);
    close(fd);
    clients.erase(it);
}

}  // namespace

int main(int argc, char** argv) {
    signal(SIGPIPE, SIG_IGN);
    int port = argc > 1 ? atoi(argv[1]) : 1883;

    int listener = socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(listener, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    sockaddr_in address{};
    address.sin_family = AF_INET;
    address.sin_addr.s_addr = htonl(INADDR_ANY);
    address.sin_port = htons(static_cast<uint16_t>(port));
    if (bind(listener, reinterpret_cast<sockaddr*>(&address),
             sizeof address) != 0) {
        perror("bind");
        return 1;
    }
    socklen_t length = sizeof address;
    getsockname(listener, reinterpret_cast<sockaddr*>(&address), &length);
    if (listen(listener, 64) != 0) {
        perror("listen");
        return 1;
    }
    printf("LISTENING %d\n", ntohs(address.sin_port));
    fflush(stdout);

    for (;;) {
        std::vector<pollfd> fds;
        fds.push_back({listener, POLLIN, 0});
        for (auto& [fd, client] : clients)
            fds.push_back({fd, static_cast<short>(
                POLLIN | (client.outbuf.empty() ? 0 : POLLOUT)), 0});
        if (poll(fds.data(), fds.size(), 1000) < 0) {
            if (errno == EINTR) continue;
            perror("poll");
            return 1;
        }
        if (fds[0].revents & POLLIN) {
            int fd = accept(listener, nullptr, nullptr);
            if (fd >= 0) {
                setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
                clients[fd].fd = fd;
                clients[fd].last_activity = time(nullptr);
            }
        }
        for (size_t i = 1; i < fds.size(); ++i) {
            int fd = fds[i].fd;
            auto it = clients.find(fd);
            if (it == clients.end()) continue;
            Client& client = it->second;
            // Drain input BEFORE acting on POLLHUP: a DISCONNECT sent
            // just before the peer closed arrives as POLLIN|POLLHUP
            // and must still clear the will (MQTT-3.14.4-3).
            if (fds[i].revents & POLLIN) {
                char buffer[65536];
                ssize_t got = recv(fd, buffer, sizeof buffer, 0);
                if (got <= 0) {
                    drop_client(fd, true);
                    continue;
                }
                client.last_activity = time(nullptr);
                client.inbuf.append(buffer, static_cast<size_t>(got));
                if (!process_input(client)) {
                    // DISCONNECT (will already cleared) or protocol
                    // error (will fires).
                    drop_client(fd, client.has_will);
                    continue;
                }
            } else if (fds[i].revents & (POLLERR | POLLHUP)) {
                drop_client(fd, true);
                continue;
            }
            if ((fds[i].revents & POLLOUT) && !client.outbuf.empty()) {
                ssize_t sent = send(fd, client.outbuf.data(),
                                    client.outbuf.size(), 0);
                if (sent < 0) {
                    drop_client(fd, true);
                    continue;
                }
                client.outbuf.erase(0, static_cast<size_t>(sent));
            }
        }
        // Keepalive enforcement (mosquitto semantics): no traffic for
        // 1.5x the client's keepalive -> dead host, will fires.  This
        // is the liveness signal multi-host failure detection rides on
        // when a peer loses power (no FIN/RST ever arrives).
        time_t now = time(nullptr);
        std::vector<int> timed_out;
        for (auto& [fd, client] : clients)
            if (client.keepalive > 0
                    && now - client.last_activity
                       > static_cast<time_t>(client.keepalive * 3 / 2))
                timed_out.push_back(fd);
        for (int fd : timed_out)
            drop_client(fd, true);
    }
}
